"""Public virtual-time API: sleep / timeout / interval / clocks.

Reference: `madsim/src/sim/time/{mod,sleep,interval}.rs` — ``sleep``,
``sleep_until``, ``timeout`` (future-vs-timer race, `time/mod.rs:122-134`),
tokio-style ``Interval`` with the three MissedTickBehavior variants
(`interval.rs:38-188`), plus ``Instant``/``SystemTime`` reads of the mock
clock. Durations are float seconds at the API; integer nanoseconds inside.

Real backend (``MADSIM_BACKEND=real`` outside a simulation): the same
functions read the OS clocks and delegate sleeping/timeouts to asyncio —
the reference's std mode re-exporting tokio::time (`std/mod.rs:1-7`,
`std/time.rs`). Interval and Instant are clock-generic and work in both
modes unmodified.
"""
from __future__ import annotations

import enum
import time as _ostime
from functools import total_ordering
from typing import Any, Awaitable, Optional

from .core import context
from .core.backend import is_real
from .core.futures import SimFuture
from .core.timewheel import NANOS_PER_SEC, to_ns

__all__ = [
    "sleep", "sleep_until", "timeout", "interval", "interval_at",
    "Interval", "MissedTickBehavior", "Instant", "monotonic", "monotonic_ns",
    "system_time", "system_time_ns", "elapsed",
]


def _time():
    return context.current_handle().time


# -- clock reads -----------------------------------------------------------

def monotonic_ns() -> int:
    """Virtual monotonic nanoseconds since simulation start (real backend:
    the OS monotonic clock)."""
    if is_real():
        return _ostime.monotonic_ns()  # detlint: allow[DET001] — real backend
    return _time().now_ns()


def monotonic() -> float:
    return monotonic_ns() / NANOS_PER_SEC


def system_time_ns() -> int:
    """Simulated wall-clock unix-epoch nanoseconds (seed-randomized base in
    2022, `time/mod.rs:27-32`), as observed by the current node — i.e. with
    the node's injected clock skew applied (``Handle.set_clock_skew``).
    Real backend: the OS wall clock."""
    if is_real():
        return _ostime.time_ns()  # detlint: allow[DET001] — real backend
    return _time().system_time_ns(context.current_node_id())


def system_time() -> float:
    return system_time_ns() / NANOS_PER_SEC


def elapsed() -> float:
    """Alias for :func:`monotonic` (reference's Instant-since-start idiom)."""
    return monotonic()


@total_ordering
class Instant:
    """Monotonic timestamp on the virtual clock."""

    __slots__ = ("ns",)

    def __init__(self, ns: int):
        self.ns = ns

    @staticmethod
    def now() -> "Instant":
        return Instant(monotonic_ns())

    def elapsed(self) -> float:
        return (monotonic_ns() - self.ns) / NANOS_PER_SEC

    def __sub__(self, other: "Instant") -> float:
        return (self.ns - other.ns) / NANOS_PER_SEC

    def __add__(self, seconds: float) -> "Instant":
        return Instant(self.ns + to_ns(seconds))

    def __eq__(self, other):
        return isinstance(other, Instant) and self.ns == other.ns

    def __lt__(self, other):
        return self.ns < other.ns

    def __hash__(self):
        return hash(self.ns)

    def __repr__(self):
        return f"Instant({self.ns}ns)"


# -- sleeping --------------------------------------------------------------

def sleep(seconds: float) -> Awaitable[None]:
    """Awaitable that completes after virtual ``seconds``. The timer is
    registered at call time (tokio Sleep semantics)."""
    return sleep_until_ns(monotonic_ns() + to_ns(seconds))


def sleep_until(instant: "Instant | float") -> Awaitable[None]:
    """Sleep until an :class:`Instant` (or float virtual-monotonic seconds)."""
    ns = instant.ns if isinstance(instant, Instant) else to_ns(instant)
    return sleep_until_ns(ns)


def sleep_until_ns(deadline_ns: int) -> Awaitable[None]:
    if is_real():
        import asyncio

        # The deadline is fixed at call time (tokio Sleep semantics); the
        # remaining delta is computed at await time so awaiting late does
        # not extend the sleep.
        async def _sleep():
            # detlint: allow[DET001] — real backend
            delta = (deadline_ns - _ostime.monotonic_ns()) / NANOS_PER_SEC
            if delta > 0:
                await asyncio.sleep(delta)

        return _sleep()
    time = _time()
    fut = SimFuture()
    if deadline_ns <= time.now_ns():
        fut.set_result(None)
    else:
        time.add_timer_at(deadline_ns, lambda: fut.set_result(None))
    return fut


# -- timeout ---------------------------------------------------------------

async def timeout(seconds: float, awaitable: Awaitable[Any]) -> Any:
    """Run ``awaitable`` with a virtual-time deadline; raises
    :class:`TimeoutError` if the deadline elapses first
    (`time/mod.rs:122-134`). Real backend: asyncio.wait_for (same abort-
    the-inner-future semantics on expiry)."""
    if is_real():
        import asyncio

        try:
            return await asyncio.wait_for(awaitable, seconds)
        except asyncio.TimeoutError:
            raise TimeoutError() from None
    handle = context.current_handle()
    result: SimFuture = SimFuture()

    async def _runner():
        try:
            value = await awaitable
        except GeneratorExit:
            raise
        except BaseException as exc:  # noqa: BLE001 — forwarded to the caller
            result.set_exception(exc)
        else:
            result.set_result(value)

    timer = handle.time.add_timer(
        to_ns(seconds), lambda: result.set_exception(TimeoutError())
    )
    inner = handle.task.spawn(_runner())
    try:
        return await result
    finally:
        timer.cancel()
        inner.abort()


# -- interval --------------------------------------------------------------

class MissedTickBehavior(enum.Enum):
    """tokio's three catch-up policies (`interval.rs:38-188`)."""

    BURST = "burst"
    DELAY = "delay"
    SKIP = "skip"


class Interval:
    def __init__(self, period: float, start_ns: Optional[int] = None,
                 missed_tick_behavior: MissedTickBehavior = MissedTickBehavior.BURST):
        if period <= 0:
            raise ValueError("interval period must be positive")
        self.period_ns = to_ns(period)
        self.missed_tick_behavior = missed_tick_behavior
        self._next_ns = start_ns if start_ns is not None else monotonic_ns()

    async def tick(self) -> Instant:
        """Wait for the next tick; returns its scheduled timestamp."""
        await sleep_until_ns(self._next_ns)
        scheduled = self._next_ns
        now = monotonic_ns()
        behavior = self.missed_tick_behavior
        if behavior is MissedTickBehavior.BURST:
            self._next_ns = scheduled + self.period_ns
        elif behavior is MissedTickBehavior.DELAY:
            self._next_ns = now + self.period_ns
        else:  # SKIP: next multiple of period after now, phase-locked to start
            missed = (now - scheduled) // self.period_ns + 1
            self._next_ns = scheduled + missed * self.period_ns
        return Instant(scheduled)

    def reset(self) -> None:
        self._next_ns = monotonic_ns() + self.period_ns


def interval(period: float) -> Interval:
    """Interval whose first tick completes immediately (tokio semantics)."""
    return Interval(period)


def interval_at(start: "Instant | float", period: float) -> Interval:
    start_ns = start.ns if isinstance(start, Instant) else to_ns(start)
    return Interval(period, start_ns=start_ns)
