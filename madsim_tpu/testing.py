"""Multi-seed test driver + @test/@main decorators.

Reference: `madsim-macros/src/lib.rs:115-153` (#[madsim::test] rewrites the
body into ``init_logger(); Builder::from_env().run(...)``) and
`madsim/src/sim/runtime/builder.rs:23-148` (env-driven seed sweep).

Environment variables (same names as the reference):

- ``MADSIM_TEST_SEED``   — base seed (default: unix-epoch seconds)
- ``MADSIM_TEST_NUM``    — number of seeds, seed..seed+num (default 1)
- ``MADSIM_TEST_JOBS``   — concurrent simulations (default 1). Host
  backend: one isolation thread per seed, ``jobs`` threads at once.
  Bridge backend: the seeds' task bodies run across ``jobs`` FORKED
  workers behind one shared device decision kernel
  (``bridge/pool.py``) — per-seed trajectories stay bit-identical to
  ``jobs=1`` (docs/bridge.md "Parallel task bodies").
- ``MADSIM_TEST_CONFIG`` — path to a TOML config file
- ``MADSIM_TEST_TIME_LIMIT``        — virtual-time limit per run, seconds
- ``MADSIM_TEST_CHECK_DETERMINISM`` — run each seed twice with RNG log/replay
- ``MADSIM_TEST_BACKEND`` — ``host`` (default) runs each seed on its own
  Runtime; ``bridge`` routes the whole seed sweep through the lockstep
  device kernel (:func:`madsim_tpu.bridge.sweep`) — same trajectories per
  seed (the bit-identical contract, tests/test_bridge.py), one batched
  decision kernel for all of them. See docs/bridge.md for when this wins.
- ``MADSIM_TEST_BATCH`` — bridge backend only: cap on concurrently live
  worlds; seeds stream through recycled kernel slots
  (``bridge.sweep(batch=...)``), so a million-seed sweep runs in bounded
  memory with unchanged per-seed trajectories.
- ``MADSIM_MINIMIZE`` — off by default. When set, a failing seed's
  fault model is MINIMIZED before the repro bundle is written: each
  non-default config knob (net loss, net latency, fs latency) becomes
  one schedule row, and the triage ddmin loop (triage/minimize.py, the
  same algebra the device sweeps use) re-runs the failing seed against
  candidate subsets until the row set is 1-minimal — the banner logs
  the row-count reduction, and the ``MADSIM_REPRO_DIR`` bundle gains a
  ``minimization`` block naming the knobs the failure actually needs
  (docs/triage.md).

On failure the driver prints the repro banner with the failing seed and the
config hash (`runtime/mod.rs:192-199`).

This thread-per-simulation sweep is the reference's only multi-simulation
parallelism (`builder.rs:118-136`) — the axis the batched device engine
(:mod:`madsim_tpu.engine`) turns into vmap over thousands of seeds.
"""
from __future__ import annotations

import copy
import functools
import inspect
import os
import sys
import threading
import time as _walltime
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Coroutine, Optional

from .core.config import Config
from .core.runtime import Runtime, init_logger

# Fault-model knob rows (MADSIM_MINIMIZE, docs/triage.md): each
# non-default Config knob maps to one opaque schedule row
# ``[0, _KNOB_OP_BASE + index, 0, 0]`` so the triage ddmin loop
# (triage/minimize.py minimize_rows) can drop/keep knobs with the exact
# machinery the device schedules use; the kept row indices map back to
# the ORIGINAL Python knob values (no int round-trip — the oracle reruns
# the exact failing config minus dropped knobs).
_KNOB_OP_BASE = 100
_KNOBS = (
    ("net.packet_loss_rate",
     lambda c: c.net.packet_loss_rate,
     lambda c, v: setattr(c.net, "packet_loss_rate", v)),
    ("net.send_latency",
     lambda c: tuple(c.net.send_latency),
     lambda c, v: setattr(c.net, "send_latency", tuple(v))),
    ("fs.io_latency",
     lambda c: tuple(c.fs.io_latency),
     lambda c, v: setattr(c.fs, "io_latency", tuple(v))),
)


def _knob_rows(config: Config):
    """(knob index, name, value) for every knob differing from the
    default fault model — the 'schedule rows' of a host test."""
    default = Config()
    return [(i, name, get(config))
            for i, (name, get, _set) in enumerate(_KNOBS)
            if get(config) != get(default)]


def _config_from_rows(config: Config, kept_idx) -> Config:
    """A default-model Config with only the kept knobs re-applied from
    ``config`` (the candidate the minimization oracle re-runs)."""
    out = Config()
    for i in kept_idx:
        name, get, set_ = _KNOBS[i]
        set_(out, get(config))
    return out


class Builder:
    """Seed-sweep driver for simulation tests."""

    def __init__(self, seed: Optional[int] = None, count: int = 1, jobs: int = 1,
                 config: Optional[Config] = None, config_path: Optional[str] = None,
                 time_limit: Optional[float] = None, check_determinism: bool = False,
                 backend: str = "host", batch: Optional[int] = None,
                 minimize: bool = False):
        # Wall-clock default seed (the reference's builder does the same):
        # deliberate nondeterminism, made reproducible by the up-front
        # banner in run() that logs the chosen seed.
        self.seed = seed if seed is not None else int(_walltime.time())  # detlint: allow[DET001]
        self.seed_from_walltime = seed is None
        self.count = max(1, count)
        self.jobs = max(1, jobs)
        self.config = config
        self.config_path = config_path
        self.time_limit = time_limit
        self.check_determinism = check_determinism
        if backend not in ("host", "bridge"):
            raise ValueError("backend must be 'host' or 'bridge'")
        self.backend = backend
        # Bridge world recycling: bound how many worlds are live at once;
        # seeds stream through the recycled slots (bridge/runtime.py).
        if batch is not None and batch < 1:
            raise ValueError("batch must be >= 1")
        self.batch = batch
        # MADSIM_MINIMIZE: ddmin the fault-model knobs of a failing seed
        # before bundling (docs/triage.md). Costs one re-run per
        # candidate knob subset, so strictly opt-in.
        self.minimize = bool(minimize)
        self._minimize_coro: Optional[Callable[[], Coroutine]] = None
        # ``module:qualname`` of the decorated test, when driven through
        # @test/@main — repro bundles (obs/bundle.py) record it so the
        # CLI can re-import and re-run the exact entry point. test_file
        # is the source path fallback for tests whose module is not
        # importable by name (scripts run as __main__).
        self.test_id: Optional[str] = None
        self.test_file: Optional[str] = None

    @staticmethod
    def from_env() -> "Builder":
        env = os.environ
        seed = int(env["MADSIM_TEST_SEED"]) if "MADSIM_TEST_SEED" in env else None
        count = int(env.get("MADSIM_TEST_NUM", "1"))
        jobs = int(env.get("MADSIM_TEST_JOBS", "1"))
        time_limit = (
            float(env["MADSIM_TEST_TIME_LIMIT"]) if "MADSIM_TEST_TIME_LIMIT" in env else None
        )
        check = env.get("MADSIM_TEST_CHECK_DETERMINISM", "") not in ("", "0", "false")
        config = None
        config_path = env.get("MADSIM_TEST_CONFIG")
        if config_path:
            with open(config_path) as f:
                config = Config.from_toml(f.read())
        batch = int(env["MADSIM_TEST_BATCH"]) if "MADSIM_TEST_BATCH" in env \
            else None
        minimize = env.get("MADSIM_MINIMIZE", "") not in ("", "0", "false")
        return Builder(seed=seed, count=count, jobs=jobs, config=config,
                       config_path=config_path, time_limit=time_limit,
                       check_determinism=check,
                       backend=env.get("MADSIM_TEST_BACKEND", "host"),
                       batch=batch, minimize=minimize)

    def _run_one(self, seed: int, make_coro: Callable[[], Coroutine]) -> Any:
        config = copy.deepcopy(self.config) if self.config is not None else None
        if self.check_determinism:
            return Runtime.check_determinism(seed, config, make_coro,
                                             time_limit=self.time_limit)
        rt = Runtime(seed=seed, config=config)
        if self.time_limit is not None:
            rt.set_time_limit(self.time_limit)
        return rt.block_on(make_coro())

    def run(self, make_coro: Callable[[], Coroutine]) -> Any:
        """Run the simulation for each seed; returns the last result.

        On failure, prints the reproduction banner and re-raises.

        Real backend (MADSIM_BACKEND=real, the reference's std-mode
        ``#[tokio::test]`` rewrite, `madsim-macros/src/lib.rs:115-153`):
        no seeds exist — the body runs once on asyncio against the real
        world; this is what the dual-mode CI matrix exercises.
        """
        from .core.backend import is_real

        if is_real():
            import asyncio

            coro = make_coro()
            if self.time_limit is not None:
                async def _limited(c=coro, limit=self.time_limit):
                    return await asyncio.wait_for(c, limit)

                return asyncio.run(_limited())
            return asyncio.run(coro)

        # Kept for MADSIM_MINIMIZE: the failure-time banner re-runs the
        # failing seed under candidate fault models through this factory.
        self._minimize_coro = make_coro
        if self.seed_from_walltime:
            # The seed came from the wall clock: log it BEFORE running, so
            # even a hang/SIGKILL (no failure banner) leaves a repro line.
            print(f"note: MADSIM_TEST_SEED not set; using wall-clock seed "
                  f"{self.seed} (run with MADSIM_TEST_SEED={self.seed} to "
                  f"reproduce)", file=sys.stderr)
        result: Any = None
        seeds = range(self.seed, self.seed + self.count)
        if self.backend == "bridge":
            return self._run_bridge(make_coro, seeds)

        def run_seed(seed: int) -> Any:
            try:
                return self._run_one(seed, make_coro)
            except BaseException as exc:
                self._print_banner(seed, error=exc)
                raise

        if self.jobs == 1:
            for seed in seeds:
                # A dedicated thread per simulation isolates thread-local
                # context exactly like the reference (`builder.rs:123`).
                result = _run_on_thread(run_seed, seed)
        else:
            # detlint: allow[DET003] — the seed-sweep driver runs outside any simulation
            with ThreadPoolExecutor(max_workers=self.jobs) as pool:
                futures = [pool.submit(run_seed, seed) for seed in seeds]
                for fut in futures:
                    result = fut.result()
        return result

    def _minimize_fault_model(self, seed: int,
                              error: BaseException) -> Optional[dict]:
        """MADSIM_MINIMIZE: ddmin the non-default fault-model knobs.

        Each knob is one opaque schedule row; the oracle re-runs the
        failing seed under the candidate config (default model + kept
        knobs) and asks "same exception type?" — exact, because the
        simulation is deterministic per (seed, config). Returns the
        bundle ``minimization`` block, or None when there is nothing to
        minimize / the failure did not re-reproduce (never raises: a
        minimization problem must not mask the original failure).
        """
        import numpy as np

        from .triage.minimize import TriageError, minimize_rows

        config = self.config if self.config is not None else Config()
        rows = _knob_rows(config)
        if not rows or self._minimize_coro is None:
            return None
        make_coro = self._minimize_coro
        err_name = type(error).__name__
        sched0 = np.zeros((len(rows), 4), np.int32)
        for r, (i, _name, _val) in enumerate(rows):
            sched0[r, 1] = _KNOB_OP_BASE + i

        def still_fails(cand: np.ndarray) -> bool:
            kept = [int(cand[r, 1]) - _KNOB_OP_BASE
                    for r in range(cand.shape[0]) if cand[r, 0] >= 0]
            cfg = _config_from_rows(config, kept)

            def body(_seed):
                # Runtime built INSIDE the isolation thread, exactly like
                # the driver's own per-seed runs (`builder.rs:123`).
                rt = Runtime(seed=seed, config=cfg)
                if self.time_limit is not None:
                    rt.set_time_limit(self.time_limit)
                return rt.block_on(make_coro())

            try:
                _run_on_thread(body, seed)
            except BaseException as exc:  # noqa: BLE001 — the oracle
                return type(exc).__name__ == err_name
            return False

        def evaluate(cands):
            return np.array([still_fails(c) for c in cands], bool)

        try:
            final, stats = minimize_rows(sched0, evaluate, weaken=False,
                                         tighten=False, max_rounds=32)
        except TriageError:
            return None  # failure did not re-reproduce under re-run
        kept = sorted(int(final[r, 1]) - _KNOB_OP_BASE
                      for r in range(final.shape[0]) if final[r, 0] >= 0)
        names = {i: name for i, name, _v in rows}
        return {
            "schema": "madsim.triage.minimization/1",
            "kind": "fault_model_knobs",
            "seed": int(seed),
            "rounds": int(stats["rounds"]),
            "candidates_evaluated": int(stats["candidates_evaluated"]),
            "original_rows": len(rows),
            "final_rows": len(kept),
            "one_minimal": bool(stats["one_minimal"]),
            "kept_knobs": [names[i] for i in kept],
            "dropped_knobs": [name for i, name, _v in rows
                              if i not in kept],
            "minimized_config": _config_from_rows(config, kept).to_dict(),
        }

    def _print_banner(self, seed: int,
                      error: Optional[BaseException] = None) -> None:
        import hashlib
        import json

        config = self.config if self.config is not None else Config()
        # The fault-model digest (net loss/latency + fs latency knobs):
        # unlike the whole-config hash it names exactly the schedule a
        # replay must match, so drift in unrelated config is visible as
        # "hash differs, fault digest same".
        cfg_dict = config.to_dict()
        fault_digest = hashlib.sha256(json.dumps(
            {"net": cfg_dict["net"], "fs": cfg_dict["fs"]},
            sort_keys=True).encode()).hexdigest()[:16]
        # Backend knobs ride the banner too: a bridge-backend failure is
        # only reproducible under the same backend + batch width, and the
        # defaults depend on the invoking environment. jobs is recorded
        # for completeness even though trajectories are jobs-invariant
        # (the bridge pool's bitwise contract, tests/test_bridge_pool.py)
        # — a pool-infrastructure failure is not.
        env_line = f"MADSIM_TEST_BACKEND={self.backend}"
        if self.batch is not None:
            env_line += f" MADSIM_TEST_BATCH={self.batch}"
        if self.backend == "bridge" and self.jobs > 1:
            env_line += f" MADSIM_TEST_JOBS={self.jobs}"
        banner = (
            "note: run with environment variable "
            f"MADSIM_TEST_SEED={seed} to reproduce this failure\n"
            f"note: config hash: MADSIM_CONFIG_HASH={config.hash()}\n"
            f"note: fault-schedule digest: MADSIM_FAULT_SHA={fault_digest}\n"
            f"note: backend: {env_line}"
        )
        minimization = None
        if self.minimize and error is not None:
            minimization = self._minimize_fault_model(seed, error)
            if minimization is not None:
                kept = minimization["kept_knobs"]
                banner += (
                    "\nnote: fault-model minimization (MADSIM_MINIMIZE): "
                    f"{minimization['original_rows']} knob row(s) -> "
                    f"{minimization['final_rows']} in "
                    f"{minimization['rounds']} round(s), "
                    f"{minimization['candidates_evaluated']} candidates; "
                    + ("failure needs: " + ", ".join(kept) if kept
                       else "failure is fault-model-independent"))
        repro_dir = os.environ.get("MADSIM_REPRO_DIR")
        if repro_dir:
            try:
                from .obs.bundle import write_test_bundle

                os.makedirs(repro_dir, exist_ok=True)
                path = write_test_bundle(
                    repro_dir, seed=seed, test_id=self.test_id,
                    test_file=self.test_file,
                    backend=self.backend, batch=self.batch,
                    config=self.config, config_path=self.config_path,
                    time_limit=self.time_limit,
                    error=(f"{type(error).__name__}: {error}"
                           if error is not None else None),
                    minimization=minimization)
                banner += (f"\nnote: repro bundle written: {path} "
                           "(replay: python -m madsim_tpu.obs replay "
                           f"--bundle {path})")
            except OSError as exc:
                banner += f"\nnote: repro bundle write failed: {exc}"
        if sys.flags.hash_randomization:
            # The reference seeds std's RandomState so HashMap
            # iteration is part of the deterministic world
            # (`rand.rs:174-182`). Python dicts are insertion-
            # ordered (safe), but str/bytes SET iteration follows
            # the per-process randomized hash — flag it so a repro
            # in a fresh process can pin it.
            banner += (
                "\nnote: str-hash randomization is on; if this test"
                " iterates sets of str/bytes, reproduce with"
                " PYTHONHASHSEED pinned (e.g. PYTHONHASHSEED=0)"
            )
        print(banner, file=sys.stderr)

    def _run_bridge(self, make_coro: Callable[[], Coroutine], seeds) -> Any:
        """Route the whole seed sweep through the batched device kernel
        (`builder.rs:118-136`, one lockstep batch instead of one thread per
        seed). Per-seed trajectories are bit-identical to the host path."""
        from .bridge import sweep, sweep_traced

        kw = dict(config=copy.deepcopy(self.config)
                  if self.config is not None else None,
                  time_limit=self.time_limit,
                  batch=self.batch)
        if self.check_determinism:
            outs_a, traces_a = sweep_traced(lambda s: make_coro(),
                                            list(seeds), **kw)
            outs_b, traces_b = sweep_traced(lambda s: make_coro(),
                                            list(seeds), **kw)
            for seed, ta, tb in zip(seeds, traces_a, traces_b):
                if ta != tb:
                    self._print_banner(seed)
                    raise RuntimeError(
                        f"non-deterministic execution detected (seed {seed}:"
                        " two bridge runs diverged)")
            outcomes = outs_a
        else:
            outcomes = sweep(lambda s: make_coro(), list(seeds),
                             jobs=self.jobs, **kw)
        result: Any = None
        for outcome in outcomes:
            if outcome.error is not None:
                self._print_banner(outcome.seed, error=outcome.error)
                raise outcome.error
            result = outcome.value
        return result


def _run_on_thread(fn: Callable[[int], Any], seed: int) -> Any:
    box: list = [None, None]

    def target():
        try:
            box[0] = fn(seed)
        except BaseException as exc:  # noqa: BLE001
            box[1] = exc

    # detlint: allow[DET003] — per-simulation isolation thread (`builder.rs:123`)
    t = threading.Thread(target=target, daemon=True)
    t.start()
    t.join()
    if box[1] is not None:
        raise box[1]
    return box[0]


def test(fn: Optional[Callable] = None, *, seed: Optional[int] = None, count: Optional[int] = None,
         jobs: Optional[int] = None, config: Optional[Config] = None,
         time_limit: Optional[float] = None, check_determinism: Optional[bool] = None,
         backend: Optional[str] = None, batch: Optional[int] = None):
    """Decorator: turn an async test fn into a multi-seed simulation test.

    ``@madsim_tpu.test`` / ``@madsim_tpu.test(count=10, time_limit=300)``.
    Env vars override nothing explicitly passed; explicit kwargs win.
    """

    def wrap(async_fn: Callable[..., Coroutine]) -> Callable:
        if not inspect.iscoroutinefunction(async_fn):
            raise TypeError("@madsim_tpu.test requires an async function")

        @functools.wraps(async_fn)
        def runner(*args, **kwargs):
            init_logger()
            b = Builder.from_env()
            b.test_id = f"{async_fn.__module__}:{async_fn.__qualname__}"
            try:
                b.test_file = inspect.getfile(async_fn)
            except TypeError:
                b.test_file = None
            if seed is not None:
                b.seed = seed
                b.seed_from_walltime = False
            if count is not None:
                b.count = max(1, count)
            if jobs is not None:
                b.jobs = max(1, jobs)
            if config is not None:
                b.config = config
            if time_limit is not None:
                b.time_limit = time_limit
            if check_determinism is not None:
                b.check_determinism = check_determinism
            if backend is not None:
                if backend not in ("host", "bridge"):
                    raise ValueError("backend must be 'host' or 'bridge'")
                b.backend = backend
            if batch is not None:
                if batch < 1:  # same contract as Builder(batch=...)
                    raise ValueError("batch must be >= 1")
                b.batch = batch
            return b.run(lambda: async_fn(*args, **kwargs))

        return runner

    if fn is not None:
        return wrap(fn)
    return wrap


def main(fn: Callable[..., Coroutine]) -> Callable:
    """Decorator for executable entry points (#[madsim::main] analog)."""

    @functools.wraps(fn)
    def runner(*args, **kwargs):
        init_logger()
        b = Builder.from_env()
        b.test_id = f"{fn.__module__}:{fn.__qualname__}"
        try:
            b.test_file = inspect.getfile(fn)
        except TypeError:
            b.test_file = None
        return b.run(lambda: fn(*args, **kwargs))

    return runner


def run(coro: Coroutine, seed: int = 0, config: Optional[Config] = None,
        time_limit: Optional[float] = None) -> Any:
    """One-shot convenience: run a coroutine in a fresh seeded Runtime.

    Real backend: runs the same coroutine on asyncio (seed/config ignored
    — there is no simulated world to seed)."""
    from .core.backend import is_real

    if is_real():
        import asyncio

        if time_limit is not None:
            async def _limited():
                return await asyncio.wait_for(coro, time_limit)

            return asyncio.run(_limited())
        return asyncio.run(coro)
    rt = Runtime(seed=seed, config=config)
    if time_limit is not None:
        rt.set_time_limit(time_limit)
    return rt.block_on(coro)
