"""Tests for mesh sharding of seed sweeps (madsim_tpu/parallel)."""
import jax
import numpy as np
import pytest

from madsim_tpu.engine import DeviceEngine, EngineConfig, RaftActor, RaftDeviceConfig
from madsim_tpu.parallel import seed_mesh, shard_worlds, sweep

RCFG = RaftDeviceConfig(n=3, n_proposals=1, buggy_double_vote=True)
ECFG = EngineConfig(n_nodes=3, outbox_cap=4, t_limit_us=3_000_000)


def test_mesh_uses_all_devices():
    mesh = seed_mesh()
    assert mesh.devices.size == len(jax.devices())
    assert mesh.devices.size == 8  # conftest forces an 8-device CPU mesh


def test_sharded_state_placement():
    eng = DeviceEngine(RaftActor(RCFG), ECFG)
    mesh = seed_mesh()
    state = shard_worlds(eng.init(np.arange(16)), mesh)
    shard_devs = {s.device for s in state.now.addressable_shards}
    assert len(shard_devs) == 8


def test_sharded_sweep_matches_single_device():
    seeds = np.arange(50)  # not a multiple of 8: exercises padding
    r8 = sweep(RaftActor(RCFG), ECFG, seeds, mesh=seed_mesh(), chunk_steps=200)
    r1 = sweep(RaftActor(RCFG), ECFG, seeds, mesh=seed_mesh(n_devices=1),
               chunk_steps=200)
    assert np.array_equal(r8.bug, r1.bug)
    for k in r8.observations:
        assert np.array_equal(r8.observations[k], r1.observations[k]), k
    assert r8.n_devices == 8 and r1.n_devices == 1


def test_sweep_finds_failing_seeds_with_repro_banner():
    res = sweep(RaftActor(RCFG), ECFG, np.arange(128), mesh=seed_mesh(),
                chunk_steps=256)
    assert res.failing_seeds  # double-vote bug must surface somewhere
    banner = res.repro_banner()
    assert f"MADSIM_TEST_SEED={res.failing_seeds[0]}" in banner


def test_sweep_early_exit_on_first_bug():
    res = sweep(RaftActor(RCFG), ECFG, np.arange(128), mesh=seed_mesh(),
                chunk_steps=64, stop_on_first_bug=True)
    assert res.bug.any()
    # Early exit: stopped well before the no-bug completion step count.
    full = sweep(RaftActor(RCFG), ECFG, np.arange(128), mesh=seed_mesh(),
                 chunk_steps=64)
    assert res.steps_run <= full.steps_run


def test_sweep_clean_config_no_bugs():
    clean = RaftDeviceConfig(n=3, n_proposals=1)
    res = sweep(RaftActor(clean), ECFG, np.arange(64), mesh=seed_mesh(),
                chunk_steps=256)
    assert not res.bug.any()
    assert res.observations["leader_elected"].all()


def test_multihost_mesh_matches_flat_mesh():
    # The DCN scale-out path: a 2-D (dcn=2 hosts x 4 chips) mesh must
    # produce bit-identical sweeps to the flat 8-chip mesh — worlds are
    # independent, only the reduction path differs (psum over both axes,
    # the cross-host hop riding DCN).
    from madsim_tpu.parallel import multihost_mesh

    mesh2d = multihost_mesh(n_hosts=2)
    assert mesh2d.devices.shape == (2, 4)
    assert mesh2d.axis_names == ("dcn", "worlds")
    clean = RaftDeviceConfig(n=3, n_proposals=1)
    flat = sweep(RaftActor(clean), ECFG, np.arange(48), mesh=seed_mesh(),
                 chunk_steps=256)
    hier = sweep(RaftActor(clean), ECFG, np.arange(48), mesh=mesh2d,
                 chunk_steps=256)
    assert np.array_equal(flat.bug, hier.bug)
    for k in flat.observations:
        assert np.array_equal(flat.observations[k], hier.observations[k]), k
    assert not hier.bug.any()


def test_compact_bucket_boundaries():
    """The shrink bucket: largest power-of-two halving that still holds
    every active world AND stays a mesh multiple."""
    from madsim_tpu.parallel.sweep import _compact_bucket

    # n_active = 0: shrink all the way to the n_dev floor.
    assert _compact_bucket(0, 64, 8) == 8
    assert _compact_bucket(0, 16, 8) == 8
    # w_cur == n_dev: already at the floor, no halving possible.
    assert _compact_bucket(0, 8, 8) == 8
    assert _compact_bucket(1, 8, 8) == 8
    # Occupancy above half: no shrink.
    assert _compact_bucket(33, 64, 8) == 64
    assert _compact_bucket(9, 16, 8) == 16
    # Power-of-two tracking of the active count.
    assert _compact_bucket(9, 64, 8) == 16
    assert _compact_bucket(5, 64, 8) == 8
    # Odd widths cannot halve at all...
    assert _compact_bucket(1, 7, 8) == 7
    # ...and halvings stop as soon as the half stops being a mesh
    # multiple (384 -> 24, because 12 % 8 != 0).
    assert _compact_bucket(1, 384, 8) == 24
    assert _compact_bucket(3, 24, 8) == 24
    # Single device: pure power-of-two decay down to the active count.
    assert _compact_bucket(1, 64, 1) == 1
    assert _compact_bucket(3, 64, 1) == 4


def test_sweep_rejects_misshaped_faults():
    """faults must be (F, 4) shared rows or (n_seeds, F, 4) per-world
    schedules; anything else used to flow silently into eng.init."""
    eng = DeviceEngine(RaftActor(RCFG), ECFG)
    seeds = np.arange(12)
    with pytest.raises(ValueError, match=r"\(F, 4\)"):
        sweep(None, ECFG, seeds, engine=eng,
              faults=np.zeros(4, np.int32), max_steps=64)
    # Mismatched leading dim: without the boundary check this would
    # silently gather wrong-world schedules via faults_p[ids] (m > n)
    # or IndexError deep inside a refill (m < n) — the error must name
    # BOTH dims so the caller sees which input is off.
    with pytest.raises(ValueError,
                       match=r"leading dim 5.*len\(seeds\)=12"):
        sweep(None, ECFG, seeds, engine=eng,
              faults=np.zeros((5, 2, 4), np.int32), max_steps=64)
    with pytest.raises(ValueError,
                       match=r"leading dim 24.*len\(seeds\)=12"):
        sweep(None, ECFG, seeds, engine=eng,
              faults=np.zeros((24, 2, 4), np.int32), max_steps=64)
    with pytest.raises(ValueError, match="per-world fault schedules"):
        sweep(None, ECFG, seeds, engine=eng,
              faults=np.zeros((12, 2, 5), np.int32), max_steps=64)
    with pytest.raises(ValueError, match="shared fault schedule"):
        sweep(None, ECFG, seeds, engine=eng,
              faults=np.zeros((2, 3), np.int32), max_steps=64)


def test_compacted_sweep_bitwise_equals_plain():
    """Straggler compaction (docs/perf.md) reorders and shrinks the world
    batch mid-sweep; per-world trajectories are position-independent, so
    every observation must come back bitwise identical, in the original
    seed order."""
    rcfg = RaftDeviceConfig(n=3, buggy_double_vote=True)
    cfg = EngineConfig(n_nodes=3, outbox_cap=4, queue_cap=64,
                      t_limit_us=1_500_000, stop_on_bug=True)
    eng = DeviceEngine(RaftActor(rcfg), cfg)
    seeds = np.arange(256)
    # Small chunks so buggy worlds freeze early and occupancy actually
    # drops across chunk boundaries (the compaction trigger).
    plain = sweep(None, cfg, seeds, engine=eng, chunk_steps=64,
                  max_steps=10_000, compact=False)
    compacted = sweep(None, cfg, seeds, engine=eng, chunk_steps=64,
                      max_steps=10_000, compact=True)
    for key in plain.observations:
        np.testing.assert_array_equal(plain.observations[key],
                                      compacted.observations[key],
                                      err_msg=key)
    assert compacted.failing_seeds == plain.failing_seeds


def test_recycled_sweep_bitwise_equals_independent_runs():
    """World recycling (docs/perf.md): seeds stream through a bounded
    batch whose retired slots are refilled on device. Every seed's
    observations must be bitwise identical to an unrecycled sweep AND to
    a truly independent single-world run — worlds are position- and
    batch-independent."""
    rcfg = RaftDeviceConfig(n=3, buggy_double_vote=True)
    cfg = EngineConfig(n_nodes=3, outbox_cap=4, queue_cap=64,
                       t_limit_us=1_500_000, stop_on_bug=True)
    eng = DeviceEngine(RaftActor(rcfg), cfg)
    seeds = np.arange(200)  # not a mesh multiple: exercises the stream tail
    plain = sweep(None, cfg, seeds, engine=eng, chunk_steps=64,
                  max_steps=10_000)
    recycled = sweep(None, cfg, seeds, engine=eng, chunk_steps=64,
                     max_steps=10_000, recycle=True, batch_worlds=48)
    for key in plain.observations:
        np.testing.assert_array_equal(plain.observations[key],
                                      recycled.observations[key],
                                      err_msg=key)
    assert recycled.failing_seeds == plain.failing_seeds
    # And against genuinely independent per-seed runs (one-world batches,
    # no sweep machinery at all) for a failing and a clean seed.
    probes = [plain.failing_seeds[0], int(np.flatnonzero(~plain.bug)[0])]
    for seed in probes:
        solo = eng.observe(eng.run(eng.init(np.asarray([seed], np.uint64)),
                                   max_steps=10_000))
        for key, v in solo.items():
            np.testing.assert_array_equal(
                recycled.observations[key][seed], v[0], err_msg=key)


def test_recycled_sweep_early_exit_before_first_refill():
    """REVIEW regression: a recycled sweep that exits before its first
    recycle/compact event (max_steps, or stop_on_first_bug — the
    documented headline hunt mode) must still report full-length,
    seed-attributed results: never-admitted seeds come back zeroed
    (bug=False), not truncated to batch_worlds."""
    rcfg = RaftDeviceConfig(n=3, buggy_double_vote=True)
    cfg = EngineConfig(n_nodes=3, outbox_cap=4, queue_cap=64,
                       t_limit_us=1_500_000, stop_on_bug=True)
    eng = DeviceEngine(RaftActor(rcfg), cfg)
    seeds = np.arange(64)
    # max_steps == one chunk: guaranteed exit before any refill/compact.
    res = sweep(None, cfg, seeds, engine=eng, chunk_steps=64, max_steps=64,
                recycle=True, batch_worlds=16)
    assert res.bug.shape == seeds.shape
    res.failing_seeds  # used to raise IndexError on the truncated array
    for key, v in res.observations.items():
        assert v.shape[0] == seeds.shape[0], key
        assert not np.asarray(v)[16:].any(), key  # never admitted: zeroed
    # Admitted seeds carry real results: identical to the same 16 seeds
    # swept alone for the same step budget.
    head = sweep(None, cfg, seeds[:16], engine=eng, chunk_steps=64,
                 max_steps=64)
    for key, v in head.observations.items():
        np.testing.assert_array_equal(res.observations[key][:16], v,
                                      err_msg=key)
    # The headline use: stop_on_first_bug over a streamed seed space.
    hunt = sweep(None, cfg, np.arange(128), engine=eng, chunk_steps=64,
                 stop_on_first_bug=True, recycle=True, batch_worlds=16)
    assert hunt.bug.shape == (128,)
    assert hunt.failing_seeds  # attribution intact whenever the stop fires


def test_recycled_utilization_beats_shrink_only():
    """Tier-1 occupancy regression for world recycling: on a synthetic
    short-tail workload — every world but one kill-alls its nodes at 1 ms
    and drains in a handful of steps, one straggler runs to an 8 s time
    limit — streaming fresh seeds into retired slots must keep the mesh
    at >= 2x the utilization of shrink-only compaction (whose bucket
    stalls at width 24 here: 384 -> 24, and 12 % 8 != 0)."""
    from madsim_tpu.engine import FAULT_KILL

    n = 384
    rcfg = RaftDeviceConfig(n=3, n_proposals=0)
    cfg = EngineConfig(n_nodes=3, outbox_cap=4, queue_cap=64,
                       t_limit_us=8_000_000)
    eng = DeviceEngine(RaftActor(rcfg), cfg)
    faults = np.zeros((n, 3, 4), np.int32)
    for node in range(3):
        faults[:, node] = [1_000, FAULT_KILL, node, 0]
    faults[7, :, 0] = -1  # the straggler: disabled rows, runs to t_limit

    seeds = np.arange(n)
    shrink = sweep(None, cfg, seeds, faults=faults, engine=eng,
                   chunk_steps=16, max_steps=100_000, compact=True)
    recycled = sweep(None, cfg, seeds, faults=faults, engine=eng,
                     chunk_steps=16, max_steps=100_000, recycle=True,
                     batch_worlds=32)
    for key in shrink.observations:
        np.testing.assert_array_equal(shrink.observations[key],
                                      recycled.observations[key],
                                      err_msg=key)
    # Calibrated ratio on this workload: ~2.35 (0.27 vs 0.115).
    assert recycled.world_utilization >= 2 * shrink.world_utilization, (
        recycled.world_utilization, shrink.world_utilization)
    # The telemetry is per chunk and covers the whole sweep.
    assert shrink.n_active_history.size == shrink.steps_run // 16
    assert (recycled.n_active_history[:-1] > 0).all()


def test_recycled_sweep_checkpoints_and_resumes(tmp_path):
    """PR 2's recycle/checkpoint restriction is lifted: the checkpoint
    persists the slot→seed index, refill cursor, and retired
    observations, so the hunt config (recycle=True) resumes — per-seed
    observations and bug flags bitwise equal to an unbroken recycled
    run's. Only genuinely unresumable width mismatches (a shrunk or
    re-batched state) still raise."""
    path = str(tmp_path / "hunt.npz")
    # Shorter virtual horizon than the module ECFG: this test runs five
    # sweeps and only needs refills + retirement, not long tails.
    ecfg = EngineConfig(n_nodes=3, outbox_cap=4, t_limit_us=1_500_000)
    eng = DeviceEngine(RaftActor(RCFG), ecfg)
    seeds = np.arange(48)
    kw = dict(chunk_steps=64, recycle=True, batch_worlds=16)

    unbroken = sweep(None, ecfg, seeds, engine=eng, max_steps=100_000, **kw)
    # Interrupted mid-stream (the cursor has refilled at least once by
    # chunk 6 at this occupancy), checkpointing as it goes.
    partial = sweep(None, ecfg, seeds, engine=eng, max_steps=64 * 6,
                    checkpoint_path=path, checkpoint_every_chunks=1, **kw)
    assert partial.steps_run < unbroken.steps_run
    # "Process restart": fresh engine, resume, run to completion.
    eng2 = DeviceEngine(RaftActor(RCFG), ecfg)
    resumed = sweep(None, ecfg, seeds, engine=eng2, max_steps=100_000,
                    checkpoint_path=path, resume=True, **kw)
    for key in unbroken.observations:
        np.testing.assert_array_equal(unbroken.observations[key],
                                      resumed.observations[key],
                                      err_msg=key)
    np.testing.assert_array_equal(unbroken.bug, resumed.bug)
    assert unbroken.failing_seeds == resumed.failing_seeds

    # Resuming under a different batch width is the unresumable case
    # the old blanket ValueError shrank to: full-shape contract only.
    with pytest.raises(ValueError, match="full-shape"):
        sweep(None, ecfg, seeds, engine=eng2, max_steps=100_000,
              chunk_steps=64, recycle=True, batch_worlds=32,
              checkpoint_path=path, resume=True)
    # A recycled checkpoint cannot silently resume as a plain sweep.
    from madsim_tpu.engine import CheckpointError

    with pytest.raises(CheckpointError, match="recycled"):
        sweep(None, ecfg, seeds, engine=eng2, max_steps=100_000,
              chunk_steps=64, checkpoint_path=path, resume=True)


def test_recycled_sweep_zero_recompiles_after_warmup():
    """Jit-cache reuse guard for DeviceEngine.__init__'s claims: a full
    recycled sweep (chunk runner + on-device compactor + vmapped refill
    init + refill select + final merge) performs ZERO new XLA
    compilations once an identical sweep has warmed the caches — counted
    via jax.log_compiles. A regression here (e.g. a jit object rebuilt
    per call, or a cache key that includes a fresh object) would silently
    pay seconds of recompiles on every sweep in a hunt loop."""
    import logging

    rcfg = RaftDeviceConfig(n=3, buggy_double_vote=True)
    cfg = EngineConfig(n_nodes=3, outbox_cap=4, queue_cap=64,
                       t_limit_us=1_500_000, stop_on_bug=True)
    eng = DeviceEngine(RaftActor(rcfg), cfg)
    seeds = np.arange(96)

    def run():
        return sweep(None, cfg, seeds, engine=eng, chunk_steps=64,
                     max_steps=10_000, recycle=True, batch_worlds=32)

    first = run()  # warmup: compiles runner, compactors, init, refill

    records = []

    class _Capture(logging.Handler):
        def emit(self, record):
            records.append(record.getMessage())

    handler = _Capture(level=logging.WARNING)
    jax_logger = logging.getLogger("jax")
    jax_logger.addHandler(handler)
    try:
        with jax.log_compiles():
            second = run()
    finally:
        jax_logger.removeHandler(handler)

    compiles = [m for m in records if "Finished XLA compilation" in m]
    assert not compiles, (
        f"{len(compiles)} new compilations in a warmed recycled sweep:\n"
        + "\n".join(compiles[:5]))
    # Same sweep, same results — the cached programs are the right ones.
    for key in first.observations:
        np.testing.assert_array_equal(first.observations[key],
                                      second.observations[key], err_msg=key)


def test_fused_sweep_zero_recompiles_across_seed_counts():
    """The PR 3 zero-recompile contract extended to the fused whole-hunt
    program: seed count, cursor, stop flag, and chunk budget are all
    traced scalars and the observation buffers are bucketed to
    _pow2_at_least(n_ids), so DIFFERENT seed counts in the same power-
    of-two bucket reuse one compiled mega-dispatch — a hunt that refills
    from a stream of varying batch sizes compiles exactly once."""
    import logging

    rcfg = RaftDeviceConfig(n=3, buggy_double_vote=True)
    cfg = EngineConfig(n_nodes=3, outbox_cap=4, queue_cap=64,
                       t_limit_us=1_500_000, stop_on_bug=True)
    eng = DeviceEngine(RaftActor(rcfg), cfg)

    def run(n_seeds):
        return sweep(None, cfg, np.arange(n_seeds), engine=eng,
                     chunk_steps=64, max_steps=10_000, fused=True,
                     recycle=True, batch_worlds=32)

    first = run(96)  # warmup: (64, 128] seed bucket, width bucket 32

    records = []

    class _Capture(logging.Handler):
        def emit(self, record):
            records.append(record.getMessage())

    handler = _Capture(level=logging.WARNING)
    jax_logger = logging.getLogger("jax")
    jax_logger.addHandler(handler)
    try:
        with jax.log_compiles():
            second = run(96)   # identical
            third = run(112)   # same bucket, different seed count
    finally:
        jax_logger.removeHandler(handler)

    compiles = [m for m in records if "Finished XLA compilation" in m]
    assert not compiles, (
        f"{len(compiles)} new compilations in a warmed fused hunt:\n"
        + "\n".join(compiles[:5]))
    for key in first.observations:
        np.testing.assert_array_equal(first.observations[key],
                                      second.observations[key], err_msg=key)
    # The third run is a real hunt over more seeds, not a cache artifact.
    assert third.observations["steps"].shape[0] == 112
    assert third.loop_stats["fused"]
