"""Tests for mesh sharding of seed sweeps (madsim_tpu/parallel)."""
import jax
import numpy as np

from madsim_tpu.engine import DeviceEngine, EngineConfig, RaftActor, RaftDeviceConfig
from madsim_tpu.parallel import seed_mesh, shard_worlds, sweep

RCFG = RaftDeviceConfig(n=3, n_proposals=1, buggy_double_vote=True)
ECFG = EngineConfig(n_nodes=3, outbox_cap=4, t_limit_us=3_000_000)


def test_mesh_uses_all_devices():
    mesh = seed_mesh()
    assert mesh.devices.size == len(jax.devices())
    assert mesh.devices.size == 8  # conftest forces an 8-device CPU mesh


def test_sharded_state_placement():
    eng = DeviceEngine(RaftActor(RCFG), ECFG)
    mesh = seed_mesh()
    state = shard_worlds(eng.init(np.arange(16)), mesh)
    shard_devs = {s.device for s in state.now.addressable_shards}
    assert len(shard_devs) == 8


def test_sharded_sweep_matches_single_device():
    seeds = np.arange(50)  # not a multiple of 8: exercises padding
    r8 = sweep(RaftActor(RCFG), ECFG, seeds, mesh=seed_mesh(), chunk_steps=200)
    r1 = sweep(RaftActor(RCFG), ECFG, seeds, mesh=seed_mesh(n_devices=1),
               chunk_steps=200)
    assert np.array_equal(r8.bug, r1.bug)
    for k in r8.observations:
        assert np.array_equal(r8.observations[k], r1.observations[k]), k
    assert r8.n_devices == 8 and r1.n_devices == 1


def test_sweep_finds_failing_seeds_with_repro_banner():
    res = sweep(RaftActor(RCFG), ECFG, np.arange(128), mesh=seed_mesh(),
                chunk_steps=256)
    assert res.failing_seeds  # double-vote bug must surface somewhere
    banner = res.repro_banner()
    assert f"MADSIM_TEST_SEED={res.failing_seeds[0]}" in banner


def test_sweep_early_exit_on_first_bug():
    res = sweep(RaftActor(RCFG), ECFG, np.arange(128), mesh=seed_mesh(),
                chunk_steps=64, stop_on_first_bug=True)
    assert res.bug.any()
    # Early exit: stopped well before the no-bug completion step count.
    full = sweep(RaftActor(RCFG), ECFG, np.arange(128), mesh=seed_mesh(),
                 chunk_steps=64)
    assert res.steps_run <= full.steps_run


def test_sweep_clean_config_no_bugs():
    clean = RaftDeviceConfig(n=3, n_proposals=1)
    res = sweep(RaftActor(clean), ECFG, np.arange(64), mesh=seed_mesh(),
                chunk_steps=256)
    assert not res.bug.any()
    assert res.observations["leader_elected"].all()


def test_multihost_mesh_matches_flat_mesh():
    # The DCN scale-out path: a 2-D (dcn=2 hosts x 4 chips) mesh must
    # produce bit-identical sweeps to the flat 8-chip mesh — worlds are
    # independent, only the reduction path differs (psum over both axes,
    # the cross-host hop riding DCN).
    from madsim_tpu.parallel import multihost_mesh

    mesh2d = multihost_mesh(n_hosts=2)
    assert mesh2d.devices.shape == (2, 4)
    assert mesh2d.axis_names == ("dcn", "worlds")
    clean = RaftDeviceConfig(n=3, n_proposals=1)
    flat = sweep(RaftActor(clean), ECFG, np.arange(48), mesh=seed_mesh(),
                 chunk_steps=256)
    hier = sweep(RaftActor(clean), ECFG, np.arange(48), mesh=mesh2d,
                 chunk_steps=256)
    assert np.array_equal(flat.bug, hier.bug)
    for k in flat.observations:
        assert np.array_equal(flat.observations[k], hier.observations[k]), k
    assert not hier.bug.any()


def test_compacted_sweep_bitwise_equals_plain():
    """Straggler compaction (docs/perf.md) reorders and shrinks the world
    batch mid-sweep; per-world trajectories are position-independent, so
    every observation must come back bitwise identical, in the original
    seed order."""
    rcfg = RaftDeviceConfig(n=3, buggy_double_vote=True)
    cfg = EngineConfig(n_nodes=3, outbox_cap=4, queue_cap=64,
                      t_limit_us=1_500_000, stop_on_bug=True)
    eng = DeviceEngine(RaftActor(rcfg), cfg)
    seeds = np.arange(256)
    # Small chunks so buggy worlds freeze early and occupancy actually
    # drops across chunk boundaries (the compaction trigger).
    plain = sweep(None, cfg, seeds, engine=eng, chunk_steps=64,
                  max_steps=10_000, compact=False)
    compacted = sweep(None, cfg, seeds, engine=eng, chunk_steps=64,
                      max_steps=10_000, compact=True)
    for key in plain.observations:
        np.testing.assert_array_equal(plain.observations[key],
                                      compacted.observations[key],
                                      err_msg=key)
    assert compacted.failing_seeds == plain.failing_seeds
