"""Core runtime tests: scheduler, determinism, node lifecycle, virtual time.

Mirrors the reference's inline suites at `task.rs:571-732`,
`time/mod.rs:221-244`, `rand.rs:268-305`, `time/system_time.rs:105-138`.
"""
import pytest

import madsim_tpu as ms
from madsim_tpu import rand, sync, task, time


def test_spawn_and_join():
    rt = ms.Runtime(seed=1)

    async def child(x):
        await time.sleep(0.01)
        return x * 2

    async def main():
        h = task.spawn(child(21))
        return await h

    assert rt.block_on(main()) == 42


def test_spawn_blocking():
    rt = ms.Runtime(seed=1)

    async def main():
        return await task.spawn_blocking(lambda: 7)

    assert rt.block_on(main()) == 7


def test_abort_task():
    rt = ms.Runtime(seed=1)

    async def forever():
        while True:
            await time.sleep(1.0)

    async def main():
        h = task.spawn(forever())
        await time.sleep(0.5)
        h.abort()
        with pytest.raises(ms.Cancelled):
            await h

    rt.block_on(main())


def test_random_select_from_ready_tasks():
    """10 seeds produce more than one distinct interleaving
    (`task.rs:571-610` analog)."""
    orders = set()
    for seed in range(10):
        rt = ms.Runtime(seed=seed)
        order = []

        async def worker(i, order=order):
            order.append(i)

        async def main(order=order):
            handles = [task.spawn(worker(i)) for i in range(10)]
            for h in handles:
                await h

        rt.block_on(main())
        orders.add(tuple(order))
    assert len(orders) > 1, "seeded scheduler must vary interleavings across seeds"


def test_same_seed_same_interleaving():
    def run(seed):
        rt = ms.Runtime(seed=seed)
        order = []

        async def worker(i):
            await time.sleep(rand.random() * 0.01)
            order.append(i)

        async def main():
            hs = [task.spawn(worker(i)) for i in range(20)]
            for h in hs:
                await h

        rt.block_on(main())
        return tuple(order)

    # Pattern from the reference: runs with seeds i/3 give exactly 3 outcomes.
    outcomes = {run(i // 3) for i in range(9)}
    assert len(outcomes) == 3


def test_deadlock_detection():
    rt = ms.Runtime(seed=1)

    async def main():
        await sync.Event().wait()  # nobody will set it

    with pytest.raises(ms.Deadlock):
        rt.block_on(main())


def test_time_limit():
    rt = ms.Runtime(seed=1)
    rt.set_time_limit(10.0)

    async def main():
        await time.sleep(100.0)

    with pytest.raises(ms.TimeLimitExceeded):
        rt.block_on(main())


def test_task_exception_fails_simulation():
    rt = ms.Runtime(seed=1)

    async def boom():
        raise ValueError("boom")

    async def main():
        task.spawn(boom())
        await time.sleep(1.0)

    with pytest.raises(ValueError, match="boom"):
        rt.block_on(main())


def test_kill_drops_tasks():
    rt = ms.Runtime(seed=1)
    counter = []

    async def ticker():
        while True:
            await time.sleep(0.1)
            counter.append(1)

    node = rt.create_node(name="n1", init=ticker)

    async def main():
        await time.sleep(0.55)
        ms.Handle.current().kill(node)
        n = len(counter)
        await time.sleep(1.0)
        assert len(counter) == n, "killed node must stop ticking"

    rt.block_on(main())


def test_restart_reruns_init():
    rt = ms.Runtime(seed=1)
    generations = []

    async def init():
        generations.append(len(generations))
        while True:
            await time.sleep(1.0)

    node = rt.create_node(name="n1", init=init)

    async def main():
        await time.sleep(0.1)
        ms.Handle.current().restart(node)
        await time.sleep(0.1)
        ms.Handle.current().restart(node)
        await time.sleep(0.1)
        assert generations == [0, 1, 2]

    rt.block_on(main())


def test_pause_resume():
    rt = ms.Runtime(seed=1)
    ticks = []

    async def ticker():
        while True:
            await time.sleep(0.1)
            ticks.append(time.monotonic())

    node = rt.create_node(name="n1", init=ticker)

    async def main():
        await time.sleep(0.35)
        ms.Handle.current().pause(node)
        n = len(ticks)
        await time.sleep(5.0)
        assert len(ticks) == n, "paused node must not run"
        ms.Handle.current().resume(node)
        await time.sleep(0.5)
        assert len(ticks) > n, "resumed node must run again"

    rt.block_on(main())


def test_sleep_ordering():
    rt = ms.Runtime(seed=1)

    async def main():
        order = []

        async def s(d, label):
            await time.sleep(d)
            order.append(label)

        hs = [task.spawn(s(0.3, "c")), task.spawn(s(0.1, "a")), task.spawn(s(0.2, "b"))]
        for h in hs:
            await h
        assert order == ["a", "b", "c"]

    rt.block_on(main())


def test_virtual_time_is_fast_and_monotonic():
    rt = ms.Runtime(seed=1)

    async def main():
        t0 = time.monotonic()
        await time.sleep(3600.0)  # an hour of virtual time
        t1 = time.monotonic()
        assert t1 - t0 >= 3600.0
        assert t1 - t0 < 3600.1

    rt.block_on(main())


def test_timeout_fires():
    rt = ms.Runtime(seed=1)

    async def main():
        with pytest.raises(TimeoutError):
            await time.timeout(0.1, time.sleep(10.0))
        # inner completes in time
        assert await time.timeout(10.0, ret42()) == 42

    async def ret42():
        await time.sleep(0.01)
        return 42

    rt.block_on(main())


def test_interval_behaviors():
    rt = ms.Runtime(seed=1)

    async def main():
        iv = time.interval(1.0)
        t0 = await iv.tick()  # immediate first tick
        t1 = await iv.tick()
        t2 = await iv.tick()
        assert abs((t1 - t0) - 1.0) < 1e-6
        assert abs((t2 - t1) - 1.0) < 1e-6

    rt.block_on(main())


def test_system_time_randomized_by_seed():
    bases = set()
    for seed in range(3):
        rt = ms.Runtime(seed=seed)

        async def main():
            return time.system_time()

        t = rt.block_on(main())
        # within 2022
        assert 1_640_995_200 <= t <= 1_640_995_200 + 366 * 24 * 3600
        bases.add(int(t))
    assert len(bases) == 3


def test_rng_deterministic_per_seed():
    def draw(seed):
        rt = ms.Runtime(seed=seed)

        async def main():
            return [rand.gen_range(0, 1000) for _ in range(16)]

        return tuple(rt.block_on(main()))

    assert draw(7) == draw(7)
    assert draw(7) != draw(8)


def test_available_parallelism():
    rt = ms.Runtime(seed=1)
    node = rt.create_node(name="big", cores=8)
    results = []

    async def check():
        results.append(task.available_parallelism())

    async def main():
        await node.spawn(check())
        assert results == [8]

    rt.block_on(main())


def test_check_determinism_passes_for_deterministic_code():
    async def main():
        total = 0
        for _ in range(10):
            await time.sleep(rand.random())
            total += rand.gen_range(0, 100)
        return total

    r = ms.Runtime.check_determinism(42, None, main)
    assert isinstance(r, int)


def test_check_determinism_catches_nondeterminism():
    state = {"runs": 0}

    async def main():
        state["runs"] += 1
        if state["runs"] == 2:
            rand.random()  # extra RNG access only on the second run
        await time.sleep(rand.random())

    with pytest.raises(ms.DeterminismError):
        ms.Runtime.check_determinism(42, None, main)


# ---------------------------------------------------------------------------
# Sync primitives: RwLock / watch / broadcast (tokio::sync parity)
# ---------------------------------------------------------------------------

def test_rwlock_readers_share_writers_exclude():
    rt = ms.Runtime(seed=3)
    events = []

    async def main():
        rw = sync.RwLock()
        gate = sync.Event()

        async def reader(name):
            async with rw.read():
                events.append(("r+", name))
                await gate.wait()
                events.append(("r-", name))

        async def writer():
            async with rw.write():
                events.append("w")

        r1 = task.spawn(reader("a"))
        r2 = task.spawn(reader("b"))
        await time.sleep(0.01)  # both readers inside
        w = task.spawn(writer())
        await time.sleep(0.01)
        assert "w" not in events  # writer excluded while readers hold
        gate.set()
        await w
        await r1
        await r2
        return events

    out = rt.block_on(main())
    # Both readers entered before the writer ran.
    assert {e for e in out[:2]} == {("r+", "a"), ("r+", "b")}
    assert out[-1] == "w" or out[-3] == "w"  # writer after reader releases


def test_rwlock_fair_queued_writer_blocks_new_readers():
    rt = ms.Runtime(seed=4)

    async def main():
        rw = sync.RwLock()
        order = []
        gate = sync.Event()

        async def hold_read():
            async with rw.read():
                await gate.wait()

        async def want_write():
            async with rw.write():
                order.append("w")

        async def late_read():
            async with rw.read():
                order.append("r")

        h = task.spawn(hold_read())
        await time.sleep(0.01)
        w = task.spawn(want_write())
        await time.sleep(0.01)
        r = task.spawn(late_read())  # queues BEHIND the writer (fairness)
        await time.sleep(0.01)
        gate.set()
        await w
        await r
        await h
        return order

    assert rt.block_on(main()) == ["w", "r"]


def test_watch_latest_value_and_skips():
    rt = ms.Runtime(seed=5)

    async def main():
        tx, rx = sync.watch(0)
        seen = []

        async def observer():
            while True:
                try:
                    await rx.changed()
                except sync.ChannelClosed:
                    return
                seen.append(rx.borrow())

        ob = task.spawn(observer())
        tx.send(1)
        tx.send(2)  # may coalesce with 1: watch is last-write-wins
        await time.sleep(0.01)
        tx.send(3)
        await time.sleep(0.01)
        tx.close()
        await ob
        return seen

    seen = rt.block_on(main())
    assert seen[-1] == 3 and 2 in seen  # latest always observed


def test_broadcast_fanout_and_lag():
    rt = ms.Runtime(seed=6)

    async def main():
        tx = sync.broadcast(2)
        a, b = tx.subscribe(), tx.subscribe()
        tx.send(1)
        tx.send(2)
        assert await a.recv() == 1 and await a.recv() == 2
        assert await b.recv() == 1
        # Overrun (capacity 2): after 3,4,5 only [4,5] remain; b (cursor at
        # message 2) lost messages 2 and 3.
        tx.send(3)
        tx.send(4)
        tx.send(5)
        with pytest.raises(sync.Lagged) as ei:
            await b.recv()
        assert ei.value.skipped == 2
        assert await b.recv() == 4
        # A new subscriber only sees the future.
        c = tx.subscribe()
        tx.send(6)
        assert await c.recv() == 6
        # a (cursor at 3) lost message 3 to the overrun, then drains.
        with pytest.raises(sync.Lagged):
            await a.recv()
        assert [await a.recv() for _ in range(2)] == [5, 6]
        tx.close()
        with pytest.raises(sync.ChannelClosed):
            await a.recv()
        return True

    assert rt.block_on(main())
