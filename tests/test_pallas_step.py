"""The fused Pallas step kernel (engine/pallas_step.py, PR "Roofline
round 2").

The one contract: ``EngineConfig(pallas=True)`` is **bitwise identical**
to the lax step across whole trajectories — the kernel body IS the
vmapped step function, so any divergence means the Pallas plumbing
(constant hoisting, input/output aliasing, block specs) corrupted
state. On CPU the kernel runs in interpret mode (the auto default), so
this file is also what keeps the TPU kernel's CPU fallback green.
``pallas=False`` stays the default: tier-1 compiles the existing lax
programs unchanged.
"""
import dataclasses

import jax
import numpy as np
import pytest

from madsim_tpu.engine import (
    DeviceEngine,
    EngineConfig,
    FAULT_KILL,
    FAULT_RESTART,
    RaftActor,
    RaftDeviceConfig,
)

SEEDS = np.arange(16)


def _leaves_equal(a, b):
    paths = [jax.tree_util.keystr(p) for p, _
             in jax.tree_util.tree_flatten_with_path(a)[0]]
    return [pth for pth, x, y in zip(paths, jax.tree.leaves(a),
                                     jax.tree.leaves(b))
            if not np.array_equal(np.asarray(x), np.asarray(y))]


@pytest.fixture(scope="module")
def raft_pair():
    """One lax + one pallas engine on the shared bug config (module
    scope: the compile dominates this file's runtime)."""
    cfg = EngineConfig(n_nodes=3, outbox_cap=4, queue_cap=64,
                       t_limit_us=1_500_000, stop_on_bug=False)
    mk = lambda: RaftActor(RaftDeviceConfig(n=3, n_proposals=2,  # noqa: E731
                                            buggy_double_vote=True))
    return (DeviceEngine(mk(), cfg),
            DeviceEngine(mk(), dataclasses.replace(cfg, pallas=True)),
            mk, cfg)


def test_pallas_off_by_default():
    cfg = EngineConfig(n_nodes=3)
    assert cfg.pallas is False and cfg.pallas_interpret is None


def test_pallas_run_bitwise_identical_incl_faults(raft_pair):
    lax_eng, pls_eng, _, _ = raft_pair
    faults = np.array([[300_000, FAULT_KILL, 0, 0],
                       [700_000, FAULT_RESTART, 0, 0]], np.int32)
    sl = lax_eng.run(lax_eng.init(SEEDS, faults=faults), 2_000)
    sp = pls_eng.run(pls_eng.init(SEEDS, faults=faults), 2_000)
    mism = _leaves_equal(sl, sp)
    assert not mism, f"pallas vs lax diverged on: {mism}"
    assert np.asarray(sp.bug).any()  # the trajectory actually found bugs


def test_pallas_run_steps_bitwise_identical(raft_pair):
    lax_eng, pls_eng, _, _ = raft_pair
    sl, sp = lax_eng.init(SEEDS), pls_eng.init(SEEDS)
    for _ in range(3):
        sl = lax_eng.run_steps(sl, 150)
        sp = pls_eng.run_steps(sp, 150)
        mism = _leaves_equal(sl, sp)
        assert not mism, f"pallas vs lax diverged mid-run on: {mism}"


def test_pallas_overflow_mid_batch_bitwise_identical():
    """A queue too small for the traffic: handlers overflow mid-outbox.
    The kernel must reproduce the partial-insert/overflow-flag dataflow
    exactly."""
    cfg = EngineConfig(n_nodes=3, outbox_cap=4, queue_cap=8,
                       t_limit_us=2_000_000, stop_on_bug=False)
    mk = lambda: RaftActor(RaftDeviceConfig(n=3, n_proposals=2))  # noqa: E731
    lax_eng = DeviceEngine(mk(), cfg)
    pls_eng = DeviceEngine(mk(), dataclasses.replace(cfg, pallas=True))
    sl = lax_eng.run(lax_eng.init(SEEDS), 3_000)
    sp = pls_eng.run(pls_eng.init(SEEDS), 3_000)
    mism = _leaves_equal(sl, sp)
    assert not mism, f"pallas vs lax diverged on: {mism}"
    assert np.asarray(sp.overflow).any(), (
        "config failed to overflow — the overflow-mid-batch path went "
        "unexercised; shrink queue_cap")


def test_pallas_world_block_grid_bitwise_identical(raft_pair):
    """pallas_block grids the kernel over the world axis (the VMEM-fit
    knob on TPU); a non-dividing block falls back to one block. Both
    must stay bitwise identical to the monolithic kernel."""
    lax_eng, _, mk, cfg = raft_pair
    sl = lax_eng.run(lax_eng.init(SEEDS), 1_000)
    for block in (4, 5):  # 5 does not divide 16: fallback path
        eng = DeviceEngine(mk(), dataclasses.replace(
            cfg, pallas=True, pallas_block=block))
        sb = eng.run(eng.init(SEEDS), 1_000)
        mism = _leaves_equal(sl, sb)
        assert not mism, f"pallas_block={block} diverged on: {mism}"


def test_pallas_block_validation():
    with pytest.raises(ValueError, match="pallas_block"):
        EngineConfig(n_nodes=3, pallas=True, pallas_block=0)


def test_pallas_state_is_donated_through_the_kernel():
    """The registry's jitted kernel step donates its input state, and
    the aliasing survives the pallas_call (input_output_aliases): the
    ledger's alias_fraction floor for engine.pallas_step rides on this.
    """
    from madsim_tpu.analysis import budgets as B

    floor = B.budget_for(B.load_ledger(), "engine.pallas_step",
                         "alias_fraction")
    assert floor is not None and floor >= 0.99, (
        "engine.pallas_step lost its full-donation floor in the ledger")
