"""Persistent compilation cache across cold processes.

A fleet of spawned workers (fleet/process.py) builds N identical
engines in N fresh JAX runtimes; without the on-disk cache each pays
the full XLA compile of the same sweep program. The contract under
test: with ``MADSIM_COMPILE_CACHE`` set, the FIRST cold process
populates the cache, a SECOND cold process loads instead of compiling
(counted via the persistent-cache hit log line), and the cached run's
results are bitwise identical to the fresh run's.
"""
import json
import os
import subprocess
import sys

import numpy as np

_CHILD = r"""
import json, logging, os, sys
import numpy as np

records = []

class _Cap(logging.Handler):
    def emit(self, record):
        records.append(record.getMessage())

from madsim_tpu.parallel.compile_cache import enable_from_env

assert enable_from_env() == os.environ["MADSIM_COMPILE_CACHE"]

# The persistent-cache layer logs hits/misses under jax's logger tree.
h = _Cap(level=logging.DEBUG)
for name in ("jax", "jax._src.compiler",
             "jax._src.compilation_cache"):
    lg = logging.getLogger(name)
    lg.setLevel(logging.DEBUG)
    lg.addHandler(h)

from madsim_tpu.engine import (DeviceEngine, EngineConfig, RaftActor,
                               RaftDeviceConfig)
from madsim_tpu.parallel.sweep import sweep

cfg = EngineConfig(n_nodes=3, outbox_cap=4, queue_cap=64,
                   t_limit_us=1_500_000, stop_on_bug=True)
eng = DeviceEngine(RaftActor(RaftDeviceConfig(n=3, buggy_double_vote=True)),
                   cfg)
res = sweep(None, cfg, np.arange(32), engine=eng, chunk_steps=64,
            max_steps=4_000)
hits = sum("persistent compilation cache hit" in m.lower()
           for m in records)
json.dump({"hits": hits,
           "failing": sorted(res.failing_seeds),
           "steps": {k: np.asarray(v).tolist()
                     for k, v in res.observations.items()
                     if k in ("steps", "bug_found", "t_us")}},
          sys.stdout)
"""


def _run_child(cache_dir):
    env = dict(os.environ,
               MADSIM_COMPILE_CACHE=str(cache_dir),
               JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=8")
    out = subprocess.run([sys.executable, "-c", _CHILD], env=env,
                         capture_output=True, text=True, timeout=600,
                         cwd=os.path.dirname(os.path.dirname(
                             os.path.abspath(__file__))))
    assert out.returncode == 0, out.stderr[-4000:]
    return json.loads(out.stdout)


def test_second_cold_process_reuses_cache(tmp_path):
    cache = tmp_path / "xla_cache"
    fresh = _run_child(cache)
    entries = {p.name for p in cache.iterdir()}
    assert entries, "first process wrote nothing to the cache"
    cached = _run_child(cache)
    # The second cold runtime LOADED the sweep programs it would
    # otherwise compile...
    assert cached["hits"] >= 1, (fresh["hits"], cached["hits"])
    # ...and added no new entries: the program set was fully covered.
    assert {p.name for p in cache.iterdir()} == entries
    # Cached-vs-fresh bitwise: a cache hit must be the SAME executable.
    assert cached["failing"] == fresh["failing"]
    for k in fresh["steps"]:
        np.testing.assert_array_equal(fresh["steps"][k],
                                      cached["steps"][k], err_msg=k)


def test_env_hook_points_jax_at_the_dir(tmp_path, monkeypatch):
    """The worker-entry hook (fleet/process.py calls this before
    building the engine): no-op when the var is unset, creates + wires
    the directory when set."""
    import jax

    from madsim_tpu.parallel import compile_cache as cc

    prev = jax.config.jax_compilation_cache_dir
    monkeypatch.delenv(cc.ENV_VAR, raising=False)
    try:
        assert cc.enable_from_env() is None
        assert jax.config.jax_compilation_cache_dir == prev
        target = tmp_path / "xla_cache"
        monkeypatch.setenv(cc.ENV_VAR, str(target))
        assert cc.enable_from_env() == str(target)
        assert jax.config.jax_compilation_cache_dir == str(target)
        assert target.is_dir()
    finally:
        jax.config.update("jax_compilation_cache_dir", prev)
