"""Tests for the multi-seed env-driven test driver (`builder.rs` analog)."""
import os

import pytest

import madsim_tpu as ms
from madsim_tpu import rand, time


def test_decorator_basic():
    runs = []

    @ms.test(seed=7, count=3)
    async def my_test():
        runs.append(ms.Handle.current().seed)

    my_test()
    assert runs == [7, 8, 9]


def test_decorator_rejects_invalid_batch():
    """@test(batch=0) must fail loudly like Builder(batch=0), not clamp."""

    @ms.test(seed=1, batch=0)
    async def my_test():
        pass

    with pytest.raises(ValueError, match="batch must be >= 1"):
        my_test()


def test_env_driven(monkeypatch):
    monkeypatch.setenv("MADSIM_TEST_SEED", "100")
    monkeypatch.setenv("MADSIM_TEST_NUM", "4")
    seeds = []

    @ms.test
    async def my_test():
        seeds.append(ms.Handle.current().seed)

    my_test()
    assert seeds == [100, 101, 102, 103]


def test_jobs_parallel(monkeypatch):
    monkeypatch.setenv("MADSIM_TEST_SEED", "1")
    monkeypatch.setenv("MADSIM_TEST_NUM", "8")
    monkeypatch.setenv("MADSIM_TEST_JOBS", "4")
    seeds = []

    @ms.test
    async def my_test():
        await time.sleep(rand.random())
        seeds.append(ms.Handle.current().seed)

    my_test()
    assert sorted(seeds) == list(range(1, 9))


def test_failing_seed_banner(capsys):
    @ms.test(seed=41, count=5)
    async def my_test():
        if ms.Handle.current().seed == 43:
            raise AssertionError("bug found at seed 43")

    with pytest.raises(AssertionError, match="bug found"):
        my_test()
    err = capsys.readouterr().err
    assert "MADSIM_TEST_SEED=43" in err
    assert "MADSIM_CONFIG_HASH=" in err


def test_wallclock_seed_logged_up_front(monkeypatch, capsys):
    # No MADSIM_TEST_SEED and no explicit seed: the builder falls back to
    # the wall clock (its one sanctioned nondeterminism — see the detlint
    # pragma at the default-seed site). The chosen seed must be logged
    # BEFORE the run, so even a hang or SIGKILL leaves a repro line.
    monkeypatch.delenv("MADSIM_TEST_SEED", raising=False)
    seen = []

    @ms.test
    async def my_test():
        seen.append(ms.Handle.current().seed)

    my_test()
    err = capsys.readouterr().err
    assert "MADSIM_TEST_SEED not set" in err
    assert f"MADSIM_TEST_SEED={seen[0]}" in err


def test_no_wallclock_banner_when_seed_pinned(monkeypatch, capsys):
    monkeypatch.setenv("MADSIM_TEST_SEED", "5")

    @ms.test
    async def env_pinned():
        pass

    env_pinned()
    monkeypatch.delenv("MADSIM_TEST_SEED")

    @ms.test(seed=9)
    async def kwarg_pinned():
        pass

    kwarg_pinned()
    assert "MADSIM_TEST_SEED not set" not in capsys.readouterr().err


def test_config_from_toml(tmp_path, monkeypatch):
    cfg_file = tmp_path / "sim.toml"
    cfg_file.write_text("[net]\npacket_loss_rate = 0.25\nsend_latency = [0.002, 0.020]\n")
    monkeypatch.setenv("MADSIM_TEST_CONFIG", str(cfg_file))
    observed = []

    @ms.test(seed=1)
    async def my_test():
        observed.append(ms.Handle.current().config.net.packet_loss_rate)

    my_test()
    assert observed == [0.25]


def test_check_determinism_env(monkeypatch):
    monkeypatch.setenv("MADSIM_TEST_CHECK_DETERMINISM", "1")
    counter = {"n": 0}

    @ms.test(seed=5)
    async def deterministic():
        await time.sleep(rand.random())

    deterministic()  # passes: runs twice, identical

    @ms.test(seed=5)
    async def nondeterministic():
        counter["n"] += 1
        if counter["n"] % 2 == 0:
            rand.random()
        await time.sleep(rand.random())

    with pytest.raises(ms.DeterminismError):
        nondeterministic()


def test_time_limit_env(monkeypatch):
    monkeypatch.setenv("MADSIM_TEST_TIME_LIMIT", "5")

    @ms.test(seed=1)
    async def my_test():
        await time.sleep(100.0)

    with pytest.raises(ms.TimeLimitExceeded):
        my_test()


def test_run_convenience():
    async def f():
        await time.sleep(1.0)
        return time.monotonic()

    t = ms.run(f(), seed=3)
    assert t >= 1.0


def test_config_toml_round_trip():
    cfg = ms.Config()
    cfg.net.packet_loss_rate = 0.1
    cfg.net.send_latency = (0.005, 0.05)
    d = cfg.to_dict()
    cfg2 = ms.Config.from_dict(d)
    assert cfg2.net.packet_loss_rate == 0.1
    assert cfg2.net.send_latency == (0.005, 0.05)
    assert cfg.hash() == cfg2.hash()
    cfg2.net.packet_loss_rate = 0.2
    assert cfg.hash() != cfg2.hash()


def test_bridge_backend_env_sweeps_through_device_kernel(monkeypatch):
    """MADSIM_TEST_BACKEND=bridge routes the @test seed sweep through
    bridge.sweep (VERDICT r4 item 1a): same seeds, same per-seed
    trajectories, batched decision kernel."""
    monkeypatch.setenv("MADSIM_TEST_BACKEND", "bridge")
    monkeypatch.setenv("MADSIM_TEST_SEED", "50")
    monkeypatch.setenv("MADSIM_TEST_NUM", "4")
    seeds = []

    @ms.test
    async def my_test():
        await time.sleep(rand.random())
        seeds.append(ms.Handle.current().seed)
        return ms.Handle.current().seed

    assert my_test() == 53  # last seed's result, like the host path
    assert seeds == [50, 51, 52, 53]


def test_bridge_backend_failing_seed_banner(capsys, monkeypatch):
    monkeypatch.setenv("MADSIM_TEST_BACKEND", "bridge")

    @ms.test(seed=41, count=5)
    async def my_test():
        await time.sleep(0.1)
        if ms.Handle.current().seed == 43:
            raise AssertionError("bug found at seed 43")

    with pytest.raises(AssertionError, match="bug found"):
        my_test()
    err = capsys.readouterr().err
    assert "MADSIM_TEST_SEED=43" in err
    assert "MADSIM_CONFIG_HASH=" in err


def test_bridge_backend_kwarg_and_check_determinism():
    @ms.test(seed=3, count=3, backend="bridge", check_determinism=True)
    async def my_test():
        await time.sleep(rand.random())
        return ms.Handle.current().seed

    assert my_test() == 5
