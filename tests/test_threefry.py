"""Known-answer + cross-backend tests for the Threefry-2x32 core."""
import numpy as np

from madsim_tpu.ops.threefry import (
    derive_stream_np,
    seed_to_key,
    threefry2x32_jax,
    threefry2x32_np,
)

# Random123 known-answer vectors for threefry2x32, 20 rounds:
# (counter, key) -> expected output.
KAT = [
    ((0x00000000, 0x00000000), (0x00000000, 0x00000000), (0x6B200159, 0x99BA4EFE)),
    ((0xFFFFFFFF, 0xFFFFFFFF), (0xFFFFFFFF, 0xFFFFFFFF), (0x1CB996FC, 0xBB002BE7)),
    ((0x243F6A88, 0x85A308D3), (0x13198A2E, 0x03707344), (0xC4923A9C, 0x483DF7A0)),
]


def test_known_answer_vectors():
    for (c0, c1), (k0, k1), (e0, e1) in KAT:
        x0, x1 = threefry2x32_np(k0, k1, c0, c1)
        assert (int(x0), int(x1)) == (e0, e1), f"ctr={c0:#x},{c1:#x} key={k0:#x},{k1:#x}"


def test_jax_matches_numpy():
    rng = np.random.default_rng(0)
    k0 = rng.integers(0, 2**32, size=128, dtype=np.uint32)
    k1 = rng.integers(0, 2**32, size=128, dtype=np.uint32)
    c0 = rng.integers(0, 2**32, size=128, dtype=np.uint32)
    c1 = rng.integers(0, 2**32, size=128, dtype=np.uint32)
    n0, n1 = threefry2x32_np(k0, k1, c0, c1)
    j0, j1 = threefry2x32_jax(k0, k1, c0, c1)
    np.testing.assert_array_equal(n0, np.asarray(j0))
    np.testing.assert_array_equal(n1, np.asarray(j1))


def test_jax_matches_numpy_under_jit_and_vmap():
    import jax
    import jax.numpy as jnp

    rng = np.random.default_rng(1)
    k0 = rng.integers(0, 2**32, size=64, dtype=np.uint32)
    k1 = rng.integers(0, 2**32, size=64, dtype=np.uint32)
    c0 = rng.integers(0, 2**32, size=64, dtype=np.uint32)
    c1 = rng.integers(0, 2**32, size=64, dtype=np.uint32)

    f = jax.jit(jax.vmap(lambda a, b, c, d: jnp.stack(threefry2x32_jax(a, b, c, d))))
    out = np.asarray(f(k0, k1, c0, c1))
    n0, n1 = threefry2x32_np(k0, k1, c0, c1)
    np.testing.assert_array_equal(out[:, 0], n0)
    np.testing.assert_array_equal(out[:, 1], n1)


def test_stream_derivation_is_stable_and_distinct():
    k = seed_to_key(0xDEADBEEF12345678)
    s0 = derive_stream_np(*k, 0)
    s1 = derive_stream_np(*k, 1)
    assert (int(s0[0]), int(s0[1])) != (int(s1[0]), int(s1[1]))
    again = derive_stream_np(*k, 0)
    assert (int(s0[0]), int(s0[1])) == (int(again[0]), int(again[1]))
