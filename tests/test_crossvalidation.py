"""Round-2 cross-engine validation surfaces: clock skew, backend
crosscheck, and the shared injected bug (buggy_double_vote) that both the
host model and the device actor must detect (VERDICT r1 items 2-3)."""
import numpy as np
import pytest

import madsim_tpu as ms
from madsim_tpu import time as simtime


def test_clock_skew_applies_to_system_time_only():
    rt = ms.Runtime(seed=9)
    rt.set_time_limit(30.0)

    async def main():
        h = ms.Handle.current()
        fast = h.create_node(name="fast", ip="10.0.0.1")
        slow = h.create_node(name="slow", ip="10.0.0.2")
        h.set_clock_skew(fast, +30.0)
        h.set_clock_skew(slow, -5.0)
        out = {}

        async def read(name):
            out[name] = (simtime.system_time(), simtime.monotonic())

        await fast.spawn(read("fast"))
        await slow.spawn(read("slow"))
        await ms.task.spawn(read("main"))
        # Wall clocks diverge by exactly the skew...
        assert out["fast"][0] - out["main"][0] == pytest.approx(30.0, abs=1e-6)
        assert out["slow"][0] - out["main"][0] == pytest.approx(-5.0, abs=1e-6)
        # ...monotonic clocks (and hence timer order) do not.
        assert out["fast"][1] == pytest.approx(out["slow"][1], abs=1e-3)
        # Hot re-skew takes effect immediately.
        h.set_clock_skew(fast, -1.0)
        await fast.spawn(read("fast2"))
        assert out["fast2"][0] - out["fast"][0] < 0  # clock jumped backwards

    rt.block_on(main())


def test_postgres_select_now_observes_server_skew():
    from madsim_tpu.shims import postgres

    rt = ms.Runtime(seed=3)
    rt.set_time_limit(120.0)

    async def main():
        h = ms.Handle.current()
        server = postgres.SimPostgresServer()

        async def serve():
            await server.serve(("10.0.0.1", 5432))

        srv = h.create_node(name="pg", ip="10.0.0.1", init=serve)
        app = h.create_node(name="app", ip="10.0.0.2")
        h.set_clock_skew(srv, +30.0)
        done = ms.sync.SimFuture()

        async def body():
            while True:
                try:
                    conn = await postgres.connect("10.0.0.1", user="t")
                    break
                except OSError:
                    await simtime.sleep(0.05)
            rows = await conn.query("SELECT now()")
            await conn.close()
            done.set_result((float(rows[0][0]), simtime.system_time()))

        app.spawn(body())
        srv_now, app_now = await done
        assert srv_now - app_now == pytest.approx(30.0, abs=0.5)

    rt.block_on(main())


def test_host_model_finds_injected_double_vote_bug():
    """Sweeping seeds on the buggy host model must trip the election-safety
    checker at a nonzero rate (cross-validated against the device rate in
    bench.py time_to_first_bug)."""
    from madsim_tpu.models.raft import (
        RaftCluster, RaftOptions, RaftInvariantViolation)

    async def world():
        cluster = RaftCluster(3, RaftOptions(persist=False,
                                             buggy_double_vote=True))
        while simtime.monotonic() < 2.0:
            await simtime.sleep(0.05)

    hits = 0
    for seed in range(24):
        rt = ms.Runtime(seed=seed)
        rt.set_time_limit(60.0)
        try:
            rt.block_on(world())
        except RaftInvariantViolation:
            hits += 1
    assert hits > 0, "buggy host model never tripped the invariant checker"


def test_device_actor_finds_injected_double_vote_bug():
    from madsim_tpu.engine import (
        DeviceEngine, EngineConfig, RaftActor, RaftDeviceConfig)

    rcfg = RaftDeviceConfig(n=3, buggy_double_vote=True)
    cfg = EngineConfig(n_nodes=3, outbox_cap=4, queue_cap=64,
                       t_limit_us=2_000_000, stop_on_bug=False)
    eng = DeviceEngine(RaftActor(rcfg), cfg)
    state = eng.run(eng.init(np.arange(512)), max_steps=4_000)
    obs = eng.observe(state)
    assert obs["bug"].sum() > 0, "device actor never flagged the bug"
    # bug_time is recorded for failing worlds.
    assert (obs["bug_time_us"][obs["bug"]] < 2**31 - 1).all()


def test_clean_device_actor_flags_no_bugs():
    from madsim_tpu.engine import (
        DeviceEngine, EngineConfig, RaftActor, RaftDeviceConfig)

    rcfg = RaftDeviceConfig(n=3)
    cfg = EngineConfig(n_nodes=3, outbox_cap=4, queue_cap=64,
                       t_limit_us=2_000_000, stop_on_bug=False)
    eng = DeviceEngine(RaftActor(rcfg), cfg)
    state = eng.run(eng.init(np.arange(512)), max_steps=4_000)
    obs = eng.observe(state)
    assert obs["bug"].sum() == 0


def test_crosscheck_cpu_devices_bit_identical():
    """Backend crosscheck machinery on two CPU devices of the test mesh
    (bench.py runs the real TPU-vs-CPU version every round)."""
    import jax

    from madsim_tpu.engine import (
        DeviceEngine, EngineConfig, RaftActor, RaftDeviceConfig)
    from madsim_tpu.engine.crosscheck import crosscheck_backends

    devs = jax.devices("cpu")
    rcfg = RaftDeviceConfig(n=3)
    cfg = EngineConfig(n_nodes=3, outbox_cap=4, queue_cap=64,
                       t_limit_us=500_000)
    eng = DeviceEngine(RaftActor(rcfg), cfg)
    out = crosscheck_backends(eng, np.arange(64), max_steps=2_000,
                              device_a=devs[0], device_b=devs[-1])
    assert out["bitwise_equal"] == 1


def test_tpc_bug_rates_comparable_host_vs_device():
    """Second cross-engine family (alongside Raft): the presumed-commit
    bug must be found by BOTH engines at comparable per-seed densities
    under the same loss rate, vote probability, and timeout ratios."""
    import madsim_tpu as ms
    from madsim_tpu.engine import DeviceEngine, EngineConfig, TPCActor, TPCDeviceConfig
    from madsim_tpu.models.tpc import run_tpc_world, TPCInvariantViolation

    loss = 0.1

    # Host: sequential seeds.
    cfg = ms.Config()
    cfg.net.packet_loss_rate = loss
    n_host = 48
    host_hits = 0
    for seed in range(n_host):
        rt = ms.Runtime(seed=seed, config=cfg)
        rt.set_time_limit(60.0)
        try:
            rt.block_on(run_tpc_world(buggy_presumed_commit=True))
        except TPCInvariantViolation:
            host_hits += 1
    host_rate = host_hits / n_host

    # Device: one vmapped batch, matched protocol constants.
    eng = DeviceEngine(
        TPCActor(TPCDeviceConfig(n=4, n_txns=6, buggy_presumed_commit=True)),
        EngineConfig(n_nodes=4, outbox_cap=5, queue_cap=64,
                     t_limit_us=2_000_000, loss_rate=loss))
    obs = eng.observe(eng.run(eng.init(np.arange(2048)), max_steps=8000))
    dev_rate = obs["bug"].mean()

    assert host_hits > 0, "host engine never found the presumed-commit bug"
    assert dev_rate > 0, "device engine never found the presumed-commit bug"
    ratio = host_rate / dev_rate
    assert 0.1 <= ratio <= 10.0, \
        f"bug densities diverge: host {host_rate:.3f} vs device {dev_rate:.3f}"

    # And both clean variants stay silent under the same chaos.
    clean_eng = DeviceEngine(
        TPCActor(TPCDeviceConfig(n=4, n_txns=6)),
        EngineConfig(n_nodes=4, outbox_cap=5, queue_cap=64,
                     t_limit_us=2_000_000, loss_rate=loss))
    assert not clean_eng.observe(
        clean_eng.run(clean_eng.init(np.arange(512)), max_steps=8000))["bug"].any()
    for seed in range(12):
        rt = ms.Runtime(seed=seed, config=cfg)
        rt.set_time_limit(60.0)
        rt.block_on(run_tpc_world())  # must not raise


def test_host_paused_leader_reelection_and_stepdown():
    """Host half of the pause cross-validation (device half:
    test_engine.py::test_pause_buffers_deliveries_and_reelects): pause the
    leader past the election timeout → a new leader is elected among the
    live nodes; on resume the stale leader sees the higher term and steps
    down (`runtime/mod.rs:251-268`, `task.rs:243-261`)."""
    from madsim_tpu.models.raft import LEADER, RaftCluster, RaftOptions

    async def world():
        h = ms.Handle.current()
        cluster = RaftCluster(3, RaftOptions(persist=False))
        old = await cluster.wait_for_leader()
        old_term = cluster.servers[old].term
        h.pause(cluster.nodes[old])

        # cluster.leader() keeps reporting the paused node's in-memory role
        # until someone outranks it — wait for a *different* leader at a
        # higher term.
        async def wait_new():
            while True:
                lead = cluster.leader()
                if (lead is not None and lead != old
                        and cluster.servers[lead].term > old_term):
                    return lead
                await simtime.sleep(0.05)

        new = await simtime.timeout(30.0, wait_new())
        h.resume(cluster.nodes[old])
        await simtime.sleep(3.0)  # buffered traffic flushes; stale term dies
        leaders = [i for i, s in cluster.servers.items() if s.role == LEADER]
        assert old not in leaders, "stale leader did not step down on resume"
        assert len(leaders) == 1
        return (old, new)

    seen = set()
    for seed in range(6):
        rt = ms.Runtime(seed=seed)
        rt.set_time_limit(120.0)
        seen.add(rt.block_on(world()))
    assert len(seen) > 1, "every seed elected the same pair — chaos is vacuous"
