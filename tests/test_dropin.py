"""Drop-in shimming of UNMODIFIED third-party packages (VERDICT r2 item 4).

The reference bar: madsim-tonic runs unmodified tonic-generated apps in-sim
(`madsim-tonic/src/lib.rs:1-8`), madsim-tokio runs unmodified tokio code
(`madsim-tokio/src/lib.rs:32-52`). The Python analogs proven here:

- ``aio.patched()`` runs the real pip-installed **tenacity** retry library
  (its own asyncio.sleep backoffs and random jitter) inside the sim,
  seed-deterministically, under packet-loss fault injection;
- ``grpc_aio.patched()`` runs client/server code written against the real
  **grpcio** ``grpc.aio`` API — handler objects built by the real
  ``grpc.method_handlers_generic_handler`` exactly as protoc-generated
  code does — over the simulated network, under chaos, deterministically.
"""
import dataclasses

import pytest

import madsim_tpu as ms
from madsim_tpu import time as mtime
from madsim_tpu.net import Endpoint, NetSim, rpc
from madsim_tpu.shims import aio, grpc_aio

tenacity = pytest.importorskip("tenacity")
grpc = pytest.importorskip("grpc")


@dataclasses.dataclass
class Ping:
    n: int


# ---------------------------------------------------------------------------
# 1. tenacity: real pip package, unmodified, in-sim under fault injection
# ---------------------------------------------------------------------------

def _tenacity_world(seed: int):
    """Flaky RPC (30% packet loss) driven by tenacity's AsyncRetrying with
    exponential jitter — every sleep and every jitter draw comes from the
    sim. Returns the full (virtual-time, attempt-count) trace."""
    cfg = ms.Config()
    cfg.net.packet_loss_rate = 0.3
    rt = ms.Runtime(seed=seed, config=cfg)
    trace = []

    async def main():
        h = ms.Handle.current()

        async def server_init():
            ep = await Endpoint.bind("10.0.0.1:700")

            async def pong(req):
                return Ping(req.n + 1)

            rpc.add_rpc_handler(ep, Ping, pong)
            await mtime.sleep(3600)

        h.create_node(name="srv", ip="10.0.0.1", init=server_init)
        cli = h.create_node(name="cli", ip="10.0.0.2")
        done = ms.sync.SimFuture()

        async def client():
            ep = await Endpoint.bind("10.0.0.2:0")
            for i in range(10):
                retryer = tenacity.AsyncRetrying(
                    stop=tenacity.stop_after_attempt(12),
                    wait=tenacity.wait_exponential_jitter(
                        initial=0.02, max=0.5, jitter=0.05),
                    retry=tenacity.retry_if_exception_type(TimeoutError),
                )
                async for attempt in retryer:
                    with attempt:
                        r = await rpc.call(ep, "10.0.0.1:700", Ping(i),
                                           timeout=0.1)
                        assert r.n == i + 1
                trace.append((round(mtime.monotonic(), 9),
                              attempt.retry_state.attempt_number))
            done.set_result(True)

        cli.spawn(client())
        assert await done

    with aio.patched():
        rt.block_on(main())
    return trace


def test_tenacity_runs_in_sim_deterministically():
    t1 = _tenacity_world(42)
    t2 = _tenacity_world(42)
    t3 = _tenacity_world(43)
    assert len(t1) == 10
    assert t1 == t2, "same seed must reproduce tenacity's retries bit-exactly"
    assert t1 != t3, "different seeds must explore different schedules"
    # The loss actually bit: some call needed more than one attempt.
    assert any(attempts > 1 for _, attempts in t1)


# ---------------------------------------------------------------------------
# 2. grpcio surface: generated-style code under grpc_aio.patched()
# ---------------------------------------------------------------------------
# The servicer/stub below are written exactly as `protoc --grpc_python_out`
# emits them (modulo protobuf classes — string codecs stand in), consuming
# only the real grpc package's public API.

class GreeterServicer:
    async def SayHello(self, request, context):
        return f"Hello, {request}!"

    async def LotsOfReplies(self, request, context):
        for i in range(3):
            yield f"{request}-{i}"


def add_GreeterServicer_to_server(servicer, server):
    rpc_method_handlers = {
        "SayHello": grpc.unary_unary_rpc_method_handler(
            servicer.SayHello,
            request_deserializer=lambda b: b.decode(),
            response_serializer=lambda s: s.encode(),
        ),
        "LotsOfReplies": grpc.unary_stream_rpc_method_handler(
            servicer.LotsOfReplies,
            request_deserializer=lambda b: b.decode(),
            response_serializer=lambda s: s.encode(),
        ),
    }
    generic_handler = grpc.method_handlers_generic_handler(
        "helloworld.Greeter", rpc_method_handlers)
    server.add_generic_rpc_handlers((generic_handler,))


class GreeterStub:
    def __init__(self, channel):
        self.SayHello = channel.unary_unary(
            "/helloworld.Greeter/SayHello",
            request_serializer=lambda s: s.encode(),
            response_deserializer=lambda b: b.decode(),
        )
        self.LotsOfReplies = channel.unary_stream(
            "/helloworld.Greeter/LotsOfReplies",
            request_serializer=lambda s: s.encode(),
            response_deserializer=lambda b: b.decode(),
        )


def _grpc_world(seed: int, chaos: bool):
    rt = ms.Runtime(seed=seed)
    rt.set_time_limit(300)
    trace = []

    async def main():
        h = ms.Handle.current()

        async def serve():
            server = grpc.aio.server()
            add_GreeterServicer_to_server(GreeterServicer(), server)
            server.add_insecure_port("10.0.0.1:50051")
            await server.start()
            await server.wait_for_termination()

        srv = h.create_node(name="server", ip="10.0.0.1", init=serve)
        cli = h.create_node(name="cli", ip="10.0.0.2")
        done = ms.sync.SimFuture()

        async def client():
            ok = 0
            while ok < 20:
                try:
                    async with grpc.aio.insecure_channel("10.0.0.1:50051") as ch:
                        stub = GreeterStub(ch)
                        while ok < 20:
                            rsp = await stub.SayHello(f"w{ok}", timeout=1.0)
                            assert rsp == f"Hello, w{ok}!"
                            streamed = [x async for x in
                                        stub.LotsOfReplies(f"s{ok}")]
                            assert streamed == [f"s{ok}-{i}" for i in range(3)]
                            trace.append((round(mtime.monotonic(), 9), ok))
                            ok += 1
                except grpc.RpcError:
                    await mtime.sleep(0.05)
            done.set_result(ok)

        cli.spawn(client())

        if chaos:
            sim = ms.simulator(NetSim)
            for _ in range(4):
                await mtime.sleep(ms.rand.thread_rng().gen_range_f64(0.2, 0.5))
                sim.disconnect2(srv.id, cli.id)
                await mtime.sleep(ms.rand.thread_rng().gen_range_f64(0.1, 0.3))
                sim.connect2(srv.id, cli.id)
        return await done

    with grpc_aio.patched():
        got = rt.block_on(main())
    return got, trace


def test_grpcio_generated_style_code_runs_in_sim():
    got, trace = _grpc_world(1, chaos=False)
    assert got == 20 and len(trace) == 20


def test_grpcio_survives_chaos_and_is_deterministic():
    a = _grpc_world(5, chaos=True)
    b = _grpc_world(5, chaos=True)
    c = _grpc_world(6, chaos=True)
    assert a[0] == 20
    assert a == b, "same seed must reproduce the whole gRPC world"
    assert a[1] != c[1]


def test_grpc_unimplemented_path_raises_rpc_error():
    rt = ms.Runtime(seed=2)

    async def main():
        server = grpc.aio.server()
        add_GreeterServicer_to_server(GreeterServicer(), server)
        server.add_insecure_port("127.0.0.1:50051")
        await server.start()
        ch = grpc.aio.insecure_channel("127.0.0.1:50051")
        mc = ch.unary_unary("/helloworld.Greeter/Nope",
                            request_serializer=lambda s: s.encode(),
                            response_deserializer=lambda b: b.decode())
        with pytest.raises(grpc.RpcError) as ei:
            await mc("x")
        assert ei.value.code() == grpc.StatusCode.UNIMPLEMENTED
        await ch.close()
        await server.stop()

    with grpc_aio.patched():
        rt.block_on(main())


def test_grpc_patch_passthrough_outside_sim():
    # Outside a simulation the patched names must return the REAL grpcio
    # objects (the `pub use tonic::*` re-export analog).
    import asyncio

    async def main():
        with grpc_aio.patched():
            ch = grpc.aio.insecure_channel("127.0.0.1:1")
            try:
                assert not isinstance(ch, grpc_aio.SimAioChannel)
            finally:
                await ch.close()

    asyncio.run(main())


def test_grpc_server_stop_drains_in_flight_rpcs():
    # grpc.aio contract: stop(grace) lets in-flight handlers finish.
    rt = ms.Runtime(seed=30)

    class Slow:
        async def Work(self, request, context):
            await mtime.sleep(0.5)
            return b"done"

    def add_to_server(servicer, server):
        handlers = {"Work": grpc.unary_unary_rpc_method_handler(
            servicer.Work)}
        server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler("t.Slow", handlers),))

    async def main():
        server = grpc.aio.server()
        add_to_server(Slow(), server)
        server.add_insecure_port("127.0.0.1:50052")
        await server.start()
        ch = grpc.aio.insecure_channel("127.0.0.1:50052")
        mc = ch.unary_unary("/t.Slow/Work")
        call = ms.task.spawn(mc(b"x"))
        await mtime.sleep(0.1)      # the RPC is now in flight
        await server.stop(grace=5.0)
        assert await call == b"done", "in-flight RPC must complete in grace"
        await ch.close()

    with grpc_aio.patched():
        rt.block_on(main())

