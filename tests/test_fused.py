"""Whole-hunt device residency (docs/perf.md "Whole-hunt residency").

The contract under test: ``sweep(fused=True)`` runs the ENTIRE
occupancy loop — compaction, retiring-tail harvest, coverage fold,
guided generation, refill, and the seed cursor — inside one device
program, and returns results bitwise identical to the serial and
pipelined host-orchestrated loops for every actor family and loop mode,
while the host issues O(1) mega-dispatches per batch: scalar ``_fetch``
batches mid-hunt, and ONE retired-observation pull at the end.

The only sanctioned divergence is ``world_utilization``: the fused tail
skips the dry-cursor shrink (every contract surface is
shrink-invariant), so a recycled hunt's tail runs at full width and the
issued-slot-steps denominator can differ. Everything else — ids,
observations, ``m_*`` metrics, occupancy history, the coverage ledger,
lineage lanes, the SearchReport — must match bit for bit.
"""
import importlib

import numpy as np
import pytest

sweep_mod = importlib.import_module("madsim_tpu.parallel.sweep")
from madsim_tpu.engine import (
    DeviceEngine,
    EngineConfig,
    PBActor,
    PBDeviceConfig,
    RaftActor,
    RaftDeviceConfig,
    TPCActor,
    TPCDeviceConfig,
)
from madsim_tpu.parallel.sweep import sweep


@pytest.fixture(scope="module")
def raft_eng():
    # Flagship family, metrics ON: the fused program carries the
    # coverage ledger fold in-loop, so the bitwise gate covers it too.
    rcfg = RaftDeviceConfig(n=3, buggy_double_vote=True)
    cfg = EngineConfig(n_nodes=3, outbox_cap=4, queue_cap=64,
                       t_limit_us=1_500_000, stop_on_bug=True,
                       metrics=True)
    return DeviceEngine(RaftActor(rcfg), cfg)


@pytest.fixture(scope="module")
def pb_eng():
    # Metrics off: the coverage-free fused program variant.
    return DeviceEngine(
        PBActor(PBDeviceConfig(n=3, n_writes=4)),
        EngineConfig(n_nodes=3, outbox_cap=4, queue_cap=64,
                     t_limit_us=1_500_000, loss_rate=0.05))


@pytest.fixture(scope="module")
def tpc_eng():
    return DeviceEngine(
        TPCActor(TPCDeviceConfig(n=4, n_txns=4,
                                 buggy_presumed_commit=True)),
        EngineConfig(n_nodes=4, outbox_cap=5, queue_cap=64,
                     t_limit_us=1_500_000, loss_rate=0.1))


@pytest.fixture(scope="module")
def paxos_eng():
    # The actorc DSL-only family: the fused chunk body is the compiled
    # spec's step, exercised through the same engine seam.
    from madsim_tpu.actorc.families.paxos import (PaxosActor, PaxosConfig,
                                                  engine_config)

    acfg = PaxosConfig()
    return DeviceEngine(PaxosActor(acfg), engine_config(acfg))


def all_loops(eng, seeds, **kw):
    ser = sweep(None, eng.cfg, seeds, engine=eng, pipeline=False, **kw)
    pip = sweep(None, eng.cfg, seeds, engine=eng, pipeline=True, **kw)
    fus = sweep(None, eng.cfg, seeds, engine=eng, fused=True, **kw)
    return ser, pip, fus


def assert_fused_bitwise(ref, fus):
    """Every contract surface bitwise; utilization deliberately NOT
    asserted (the fused tail runs at full width — module docstring)."""
    assert ref.steps_run == fus.steps_run
    np.testing.assert_array_equal(ref.n_active_history,
                                  fus.n_active_history)
    np.testing.assert_array_equal(ref.n_active_chunks,
                                  fus.n_active_chunks)
    for k in ref.observations:
        np.testing.assert_array_equal(ref.observations[k],
                                      fus.observations[k], err_msg=k)
    assert ref.failing_seeds == fus.failing_seeds
    assert ref.loop_stats["chunks"] == fus.loop_stats["chunks"]
    if ref.coverage is not None:
        np.testing.assert_array_equal(ref.coverage.hits,
                                      fus.coverage.hits)
        np.testing.assert_array_equal(ref.coverage.first_seen_seed,
                                      fus.coverage.first_seen_seed)
        np.testing.assert_array_equal(ref.coverage.novelty_curve,
                                      fus.coverage.novelty_curve)


def test_fused_matches_serial_raft_all_modes(raft_eng):
    """Every fused-legal loop mode of the flagship family: full-width
    with a BINDING max_steps cap (worlds are still active when the
    budget runs out — the truncated tail must harvest identically),
    recycled natural drain, and the recycled early-stop combination
    (early exit with a mega-dispatch in flight must not overrun).  The
    pipelined leg rides only the first two modes — serial==pipelined
    for every mode is already tier-1-gated in test_sweep_pipeline, so
    the new claim here is fused==serial.  Every mode variant traces its
    own fused mega-program (~5s each even on a warm persistent cache),
    so modes earn their slot by exercising a distinct fused code path
    — a plain uncapped full-width mode would re-trace a whole program
    to re-prove the drain that the recycled mode and the family tests
    below already gate."""
    seeds = np.arange(144)  # not a mesh multiple: stream tail exercised
    for i, kw in enumerate((
            dict(chunk_steps=64, max_steps=128),
            dict(chunk_steps=64, max_steps=1_280,
                 recycle=True, batch_worlds=48),
            dict(chunk_steps=64, max_steps=10_000,
                 stop_on_first_bug=True, recycle=True,
                 batch_worlds=48))):
        ser = sweep(None, raft_eng.cfg, seeds, engine=raft_eng,
                    pipeline=False, **kw)
        fus = sweep(None, raft_eng.cfg, seeds, engine=raft_eng,
                    fused=True, **kw)
        assert_fused_bitwise(ser, fus)
        if i == 0:
            # The cap must actually bind for the truncated-tail claim
            # (raft double-vote worlds drain naturally by ~step 256).
            assert ser.steps_run == 128
            assert np.asarray(ser.n_active_history)[-1] > 0
        if i < 2:
            pip = sweep(None, raft_eng.cfg, seeds, engine=raft_eng,
                        pipeline=True, **kw)
            assert_fused_bitwise(pip, fus)
    assert fus.loop_stats["fused"] and not fus.loop_stats["pipelined"]
    assert not ser.loop_stats["fused"] and not pip.loop_stats["fused"]


@pytest.mark.parametrize("family", ["pb", "tpc", "paxos"])
def test_fused_matches_serial_families(family, request):
    """Drain hunts of the remaining families (pb/tpc hand-written,
    paxos actorc-compiled), serial-vs-fused; the actorc family also
    rides the recycled refill path.  The pipelined loop is
    family-agnostic host logic already gated against serial per family
    in its own suite, and against fused on the flagship above — and
    recycled pb/tpc would re-trace two more whole programs to re-prove
    the refill seam that raft, paxos, and the guided pair already
    gate."""
    eng = request.getfixturevalue(f"{family}_eng")
    seeds = np.arange(64)
    modes = [dict(chunk_steps=64, max_steps=2_500)]
    if family == "paxos":
        modes.append(dict(chunk_steps=64, max_steps=2_500,
                          recycle=True, batch_worlds=32))
    for kw in modes:
        ser = sweep(None, eng.cfg, seeds, engine=eng, pipeline=False,
                    **kw)
        fus = sweep(None, eng.cfg, seeds, engine=eng, fused=True, **kw)
        assert_fused_bitwise(ser, fus)


# ---------------------------------------------------------------------------
# Guided hunts: harvest + generate + lineage inside the fused loop
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def hunt():
    from madsim_tpu.search import (GuidedPairActor, GuidedPairConfig,
                                   engine_config, family_schedule)
    from madsim_tpu.search.family import HUNT_NODES, HUNT_ROWS

    acfg = GuidedPairConfig(n=HUNT_NODES)
    cfg = engine_config(acfg)
    eng = DeviceEngine(GuidedPairActor(acfg), cfg)
    tmpl = family_schedule(HUNT_ROWS, acfg)
    return eng, cfg, tmpl


@pytest.mark.parametrize("guided", [True, False])
def test_fused_guided_hunt_bitwise(hunt, guided):
    """The guided (and matched random-baseline) hunt: child bytes,
    corpus decisions, lineage lanes, operator credits, and the
    SearchReport are identical when the harvest+generate fold runs as a
    ``lax.cond`` branch of the fused loop instead of a host-dispatched
    program at each refill — it is the same traced callable
    (search/generate.py ``generate_body``) either way."""
    from madsim_tpu.search.family import hunt_search_config

    eng, cfg, tmpl = hunt
    seeds = np.arange(96)
    kw = dict(engine=eng, faults=tmpl, max_steps=10_000_000,
              search=hunt_search_config(guided), recycle=True,
              batch_worlds=32, chunk_steps=32)
    ser = sweep(None, cfg, seeds, pipeline=False, **kw)
    fus = sweep(None, cfg, seeds, fused=True, **kw)
    assert_fused_bitwise(ser, fus)
    # SearchReport: the whole guided outcome surface.
    rs, rf = ser.search, fus.search
    assert (rs.generations, rs.inserted, rs.corpus_size) == \
        (rf.generations, rf.inserted, rf.corpus_size)
    for field in ("corpus_sched", "corpus_sig", "corpus_score",
                  "corpus_filled", "schedules", "corpus_entry",
                  "corpus_depth"):
        np.testing.assert_array_equal(getattr(rs, field),
                                      getattr(rf, field), err_msg=field)
    assert rs.operator_stats == rf.operator_stats
    for lane in ("parent1", "parent2", "ops", "depth"):
        np.testing.assert_array_equal(getattr(rs.lineage, lane),
                                      getattr(rf.lineage, lane),
                                      err_msg=lane)
    # Triage attribution: the materialized per-seed schedules.
    np.testing.assert_array_equal(ser.triage_ctx.faults,
                                  fus.triage_ctx.faults)


# ---------------------------------------------------------------------------
# Refusals: the checkpoint-interplay decision (docs/perf.md)
# ---------------------------------------------------------------------------

def test_fused_refuses_checkpoint(raft_eng, tmp_path):
    """Decision, tested: fused + checkpoint_path is a pointed refusal —
    no host-visible mid-hunt boundary exists where state, cursor, and
    retired observations are simultaneously consistent."""
    with pytest.raises(ValueError, match="fused=True cannot checkpoint"):
        sweep(None, raft_eng.cfg, np.arange(8), engine=raft_eng,
              fused=True, checkpoint_path=str(tmp_path / "x.npz"))


def test_fused_refuses_compact(raft_eng):
    with pytest.raises(ValueError, match="fused=True has no shrink"):
        sweep(None, raft_eng.cfg, np.arange(8), engine=raft_eng,
              fused=True, compact=True)


# ---------------------------------------------------------------------------
# Dispatch economics: the tentpole's acceptance gate
# ---------------------------------------------------------------------------

def test_fused_dispatch_reduction_and_fetch_discipline(raft_eng,
                                                       monkeypatch):
    """The headline numbers, counted through the ``_fetch`` hook: on the
    pinned recycled-hunt shape the fused loop needs >= 4x fewer host
    dispatches per seed than the pipelined loop, with zero added
    mid-loop fetches — one scalar batch per mega-dispatch and ONE
    end-of-hunt retirement pull, total."""
    calls = []
    real_fetch = sweep_mod._fetch

    def counting_fetch(tree):
        out = real_fetch(tree)
        import jax
        nbytes = sum(np.asarray(x).nbytes for x in jax.tree.leaves(out))
        calls.append(nbytes)
        return out

    monkeypatch.setattr(sweep_mod, "_fetch", counting_fetch)
    # Same shape as the recycled mode above: the programs are already
    # compiled, this test pays execution + the counting hook only.
    seeds = np.arange(144)
    kw = dict(chunk_steps=64, max_steps=1_280, recycle=True,
              batch_worlds=48)
    pip = sweep(None, raft_eng.cfg, seeds, engine=raft_eng, **kw)
    calls.clear()
    fus = sweep(None, raft_eng.cfg, seeds, engine=raft_eng, fused=True,
                **kw)
    assert_fused_bitwise(pip, fus)
    st = fus.loop_stats
    # One scalar batch per mega-dispatch, one retirement pull — nothing
    # else crosses the boundary.
    assert st["scalar_fetches"] == st["dispatches"]
    assert st["retire_fetches"] == 1
    assert len(calls) == st["scalar_fetches"] + 1
    # The mid-loop pulls are scalars + the two K-wide history lanes —
    # bounded by the chunk budget, never a per-world or per-seed array.
    scalar_bytes = calls[:-1]
    assert max(scalar_bytes) <= 8 * st["superstep_max"] + 64, scalar_bytes
    # >= 4x fewer dispatches per seed than the pipelined loop (the
    # tier-1 regression gate of the bench acceptance criterion).
    assert st["dispatches_per_seed"] * 4 <= \
        pip.loop_stats["dispatches_per_seed"], (st, pip.loop_stats)
    assert st["seeds_per_dispatch"] >= \
        4 * pip.loop_stats["seeds_per_dispatch"]
    # The whole hunt refilled on device, host cursor mirrors agree.
    assert st["epochs_on_device"] >= 1
    assert pip.loop_stats["epochs_on_device"] == 0


def test_fused_zero_step_budget_runs_no_chunks(raft_eng):
    """max_steps <= 0: zero chunks, but the live (init-state)
    observations still land — the serial loop's final observe() of an
    unstepped batch, reproduced by the zero-chunk pass-through
    mega-dispatch."""
    ser, pip, fus = all_loops(raft_eng, np.arange(8), chunk_steps=64,
                              max_steps=0)
    assert_fused_bitwise(ser, fus)
    assert fus.steps_run == 0
    assert fus.loop_stats["chunks"] == 0


def test_fused_loop_stats_schema(raft_eng):
    """The documented loop_stats schema on the fused path, plus the two
    new dispatch-economics keys on EVERY path (make smoke asserts them
    through bench_results.json)."""
    res = sweep(None, raft_eng.cfg, np.arange(48), engine=raft_eng,
                chunk_steps=64, max_steps=2_048, fused=True)
    ls = res.loop_stats
    documented = {"device_wait_s", "host_decision_s", "scalar_fetches",
                  "retire_fetches", "dispatch_depth",
                  "dispatches_per_seed", "seeds_per_dispatch",
                  "epochs_on_device", "pipelined", "fused",
                  "superstep_max", "chunk_steps", "chunks", "dispatches",
                  "chunks_per_dispatch", "dispatch_s", "retire_wait_s",
                  "loop_wall_s"}
    assert documented <= set(ls), sorted(ls)
    assert ls["fused"] is True and ls["pipelined"] is False
    assert isinstance(ls["seeds_per_dispatch"], float)
    assert isinstance(ls["epochs_on_device"], int)
    assert ls["seeds_per_dispatch"] == pytest.approx(
        48 / ls["dispatches"], abs=1e-3)
    # And on the host paths the keys exist with the fused-off values.
    for pipeline in (True, False):
        res = sweep(None, raft_eng.cfg, np.arange(48), engine=raft_eng,
                    chunk_steps=64, max_steps=2_048, pipeline=pipeline)
        assert {"seeds_per_dispatch", "epochs_on_device",
                "fused"} <= set(res.loop_stats)
        assert res.loop_stats["epochs_on_device"] == 0
        assert res.loop_stats["fused"] is False
