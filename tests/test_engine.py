"""Tests for the batched device engine (madsim_tpu/engine)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from madsim_tpu.core.rng import GlobalRng
from madsim_tpu.engine import (
    DeviceEngine, EngineConfig, RaftActor, RaftDeviceConfig,
    FAULT_KILL, FAULT_RESTART, FAULT_CLOG_NODE, FAULT_UNCLOG_NODE,
    FAULT_SET_LATENCY, FAULT_SET_LOSS, FAULT_PAUSE, FAULT_RESUME, INF_TIME,
)
from madsim_tpu.engine.core import STREAM_DEVICE
from madsim_tpu.engine.queue import Event, empty_queue, pop, push
from madsim_tpu.engine.rng import make_rng, next_u32


RCFG = RaftDeviceConfig(n=3, n_proposals=2)
ECFG = EngineConfig(n_nodes=3, outbox_cap=4, t_limit_us=3_000_000)


@pytest.fixture(scope="module")
def raft_engine():
    return DeviceEngine(RaftActor(RCFG), ECFG)


# ---------------------------------------------------------------------------
# Event queue
# ---------------------------------------------------------------------------

def test_queue_orders_by_time():
    q = empty_queue(8, 4)
    for t in [50, 10, 30]:
        q, ok = push(q, Event.make(time=t, kind=t, payload_words=4))
        assert bool(ok)
    times = []
    for _ in range(3):
        q, ev, found = pop(q)
        assert bool(found)
        times.append(int(ev.time))
    assert times == [10, 30, 50]
    q, _, found = pop(q)
    assert not bool(found)


def test_queue_overflow_reported():
    q = empty_queue(2, 4)
    q, ok1 = push(q, Event.make(time=1, kind=0, payload_words=4))
    q, ok2 = push(q, Event.make(time=2, kind=0, payload_words=4))
    q, ok3 = push(q, Event.make(time=3, kind=0, payload_words=4))
    assert bool(ok1) and bool(ok2) and not bool(ok3)


def test_queue_slot_reuse():
    q = empty_queue(2, 4)
    q, _ = push(q, Event.make(time=1, kind=1, payload_words=4))
    q, _ = push(q, Event.make(time=2, kind=2, payload_words=4))
    q, ev, _ = pop(q)
    assert int(ev.kind) == 1
    q, ok = push(q, Event.make(time=3, kind=3, payload_words=4))
    assert bool(ok)
    q, ev, _ = pop(q)
    assert int(ev.kind) == 2


# ---------------------------------------------------------------------------
# Device RNG ↔ host RNG stream parity
# ---------------------------------------------------------------------------

def test_device_rng_matches_host_stream():
    # Device draw i == low 32 bits of the host GlobalRng's u64 draw i for the
    # same (seed, stream): both address Threefry block i of the derived key.
    for seed in (0, 1, 0xDEADBEEF, (1 << 63) + 7):
        host = GlobalRng(seed, stream=STREAM_DEVICE)
        rng = make_rng(jnp.uint32(seed & 0xFFFFFFFF), jnp.uint32(seed >> 32),
                       STREAM_DEVICE)
        for _ in range(8):
            dev_draw, rng = next_u32(rng)
            assert int(dev_draw) == host.next_u64() & 0xFFFFFFFF


# ---------------------------------------------------------------------------
# Engine determinism & batching
# ---------------------------------------------------------------------------

def test_engine_bit_exact_determinism(raft_engine):
    eng = raft_engine
    s1 = eng.run(eng.init(np.arange(16)), max_steps=4000)
    s2 = eng.run(eng.init(np.arange(16)), max_steps=4000)
    for a, b in zip(jax.tree.leaves(s1), jax.tree.leaves(s2)):
        assert np.array_equal(np.asarray(a), np.asarray(b))


def test_engine_seeds_differ(raft_engine):
    obs = raft_engine.observe(raft_engine.run(raft_engine.init(np.arange(8)), 4000))
    # Different seeds must explore different schedules: election times differ.
    assert len(set(obs["first_leader_time_us"].tolist())) > 1


def test_run_steps_matches_run(raft_engine):
    eng = raft_engine
    a = eng.run(eng.init(np.arange(4)), max_steps=4000)
    b = eng.init(np.arange(4))
    for _ in range(16):
        b = eng.run_steps(b, 250)
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        assert np.array_equal(np.asarray(x), np.asarray(y))


# ---------------------------------------------------------------------------
# Raft actor semantics
# ---------------------------------------------------------------------------

def test_raft_elects_and_commits(raft_engine):
    obs = raft_engine.observe(raft_engine.run(raft_engine.init(np.arange(32)), 4000))
    assert obs["leader_elected"].all()
    assert (obs["max_commit"] == RCFG.n_proposals).all()
    assert not obs["bug"].any()
    assert not obs["overflow"].any()


def test_raft_reelects_after_leader_kill(raft_engine):
    # Kill node 0 at 400 ms (after the typical first election), restart at
    # 900 ms. Worlds where node 0 led must re-elect; none may violate safety.
    faults = np.array([[400_000, FAULT_KILL, 0, 0],
                       [900_000, FAULT_RESTART, 0, 0]], np.int32)
    st = raft_engine.run(raft_engine.init(np.arange(64), faults=faults), 8000)
    obs = raft_engine.observe(st)
    assert obs["leader_elected"].all()
    assert not obs["bug"].any()
    assert (obs["elections_won"] >= 2).any()  # some world had node 0 as leader


def test_raft_partition_blocks_then_heals():
    # Clog node 0 from 350 ms to 1.5 s: the cluster (n=3) retains quorum and
    # keeps/elects a leader among {1, 2}; after heal, proposals still commit.
    rcfg = RaftDeviceConfig(n=3, n_proposals=2, propose_start_us=2_000_000)
    cfg = EngineConfig(n_nodes=3, outbox_cap=4, t_limit_us=4_000_000)
    eng = DeviceEngine(RaftActor(rcfg), cfg)
    faults = np.array([[350_000, FAULT_CLOG_NODE, 0, 0],
                       [1_500_000, FAULT_UNCLOG_NODE, 0, 0]], np.int32)
    obs = eng.observe(eng.run(eng.init(np.arange(32), faults=faults), 10_000))
    assert obs["leader_elected"].all()
    assert not obs["bug"].any()
    assert (obs["max_commit"] == 2).all()


def test_raft_total_loss_prevents_election():
    cfg = EngineConfig(n_nodes=3, outbox_cap=4, t_limit_us=1_500_000,
                       loss_rate=1.0)
    eng = DeviceEngine(RaftActor(RaftDeviceConfig(n=3)), cfg)
    obs = eng.observe(eng.run(eng.init(np.arange(8)), 6000))
    assert not obs["leader_elected"].any()   # no quorum without messages
    assert not obs["bug"].any()


def test_raft_survives_packet_loss():
    cfg = EngineConfig(n_nodes=3, outbox_cap=4, t_limit_us=8_000_000,
                       loss_rate=0.2)
    eng = DeviceEngine(RaftActor(RaftDeviceConfig(n=3, n_proposals=1)), cfg)
    obs = eng.observe(eng.run(eng.init(np.arange(16)), 20_000))
    assert obs["leader_elected"].all()
    assert not obs["bug"].any()


def test_injected_bug_is_found_and_stops_world():
    rcfg = RaftDeviceConfig(n=3, buggy_double_vote=True)
    cfg = EngineConfig(n_nodes=3, outbox_cap=4, t_limit_us=3_000_000)
    eng = DeviceEngine(RaftActor(rcfg), cfg)
    st = eng.run(eng.init(np.arange(256)), 4000)
    obs = eng.observe(st)
    assert obs["bug"].any()            # the seed sweep finds the bug
    assert not obs["bug"].all()        # ... only under some interleavings
    hit = obs["bug"]
    # stop_on_bug freezes buggy worlds at the moment of violation.
    assert (obs["bug_time_us"][hit] <= obs["now_us"][hit]).all()
    assert (obs["bug_time_us"][~hit] == int(INF_TIME)).all()


def test_won_terms_bitset_catches_historical_double_win():
    # Election-safety history must survive later wins: node A wins term 2,
    # then term 3; node B then wins term 2. A scalar last-won-term record
    # is overwritten by A's term-3 win and misses B's duplicate; the
    # won_terms bitset keeps the full history and flags it at win time.
    from madsim_tpu.engine.raft_actor import (
        CANDIDATE, K_VOTEREPLY, WON_WORDS, RaftActor)

    rcfg = RaftDeviceConfig(n=3)
    cfg = EngineConfig(n_nodes=3, outbox_cap=4, t_limit_us=3_000_000)
    actor = RaftActor(rcfg)
    rng = make_rng(jnp.uint32(0), jnp.uint32(0), STREAM_DEVICE)
    s, _, rng = actor.init(cfg, rng)

    def force_win(s, rng, me, term):
        # Put `me` in CANDIDATE at `term` holding its own vote, then deliver
        # a granted VoteReply from one peer -> majority (2/3) -> win.
        s = s._replace(
            term=s.term.at[me].set(term),
            role=s.role.at[me].set(CANDIDATE),
            votes=s.votes.at[me].set(1 << me),
            voted_for=s.voted_for.at[me].set(me))
        voter = (me + 1) % 3
        ev = Event.make(time=0, kind=K_VOTEREPLY,
                        payload_words=cfg.payload_words,
                        src=voter, dst=me, payload=[term, 1, voter])
        s, _ob, rng, bug = actor.handle(cfg, s, ev, jnp.int32(0), rng)
        return s, rng, bool(bug)

    s, rng, bug = force_win(s, rng, 0, 2)     # A wins term 2
    assert not bug
    s, rng, bug = force_win(s, rng, 0, 3)     # A wins term 3 too
    assert not bug
    s, rng, bug = force_win(s, rng, 1, 2)     # B re-wins term 2: violation
    assert bug
    # Higher words track independently of word 0.
    s, rng, bug = force_win(s, rng, 0, 40)
    assert not bug
    s, rng, bug = force_win(s, rng, 2, 40)
    assert bug
    s, rng, bug = force_win(s, rng, 0, 100)
    assert not bug
    s, rng, bug = force_win(s, rng, 1, 101)
    assert not bug
    # Terms >= 32*WON_WORDS saturate into the top bit: distinct huge terms
    # alias (a documented over-approximation is still a caught duplicate).
    s, rng, bug = force_win(s, rng, 0, 32 * WON_WORDS + 6)
    assert not bug
    s, rng, bug = force_win(s, rng, 1, 32 * WON_WORDS + 99)
    assert bug


def test_five_node_cluster():
    # Proposals are scheduled after the restarts settle: scheduled client
    # proposals have no retry loop, so ones fired into a leaderless window
    # are (correctly) lost.
    rcfg = RaftDeviceConfig(n=5, n_proposals=3, log_cap=16,
                            propose_start_us=2_500_000)
    cfg = EngineConfig(n_nodes=5, outbox_cap=6, t_limit_us=5_000_000)
    eng = DeviceEngine(RaftActor(rcfg), cfg)
    faults = np.array([[500_000, FAULT_KILL, 0, 0],
                       [700_000, FAULT_KILL, 1, 0],
                       [1_600_000, FAULT_RESTART, 0, 0],
                       [1_800_000, FAULT_RESTART, 1, 0]], np.int32)
    obs = eng.observe(eng.run(eng.init(np.arange(24), faults=faults), 20_000))
    assert obs["leader_elected"].all()
    assert not obs["bug"].any()
    assert (obs["max_commit"] == 3).all()


def test_trace_replays_failing_seed():
    # The repro loop: sweep finds a failing seed -> trace it -> the trace
    # shows ordered events with virtual times and the bug-raise point.
    rcfg = RaftDeviceConfig(n=3, buggy_double_vote=True)
    cfg = EngineConfig(n_nodes=3, outbox_cap=4, t_limit_us=2_000_000,
                       stop_on_bug=True)
    eng = DeviceEngine(RaftActor(rcfg), cfg)
    obs = eng.observe(eng.run(eng.init(np.arange(64)), 4000))
    assert obs["bug"].any()
    failing = int(np.argmax(obs["bug"]))

    trace = eng.trace(failing, max_steps=4000)
    assert trace, "a failing world has events"
    times = [e["t_us"] for e in trace]
    assert times == sorted(times), "events replay in virtual-time order"
    kinds = {e["kind"] for e in trace}
    assert "Election" in kinds and "RequestVote" in kinds
    bug_steps = [e for e in trace if e.get("bug_raised")]
    assert len(bug_steps) == 1, "exactly one bug-raise point"
    assert bug_steps[0]["t_us"] == int(obs["bug_time_us"][failing])
    # Tracing is a pure replay: same seed, same trace.
    assert eng.trace(failing, max_steps=4000) == trace


def test_trace_includes_faults():
    rcfg = RaftDeviceConfig(n=3)
    cfg = EngineConfig(n_nodes=3, outbox_cap=4, t_limit_us=1_500_000)
    eng = DeviceEngine(RaftActor(rcfg), cfg)
    faults = np.array([[400_000, FAULT_KILL, 1, 0],
                       [800_000, FAULT_RESTART, 1, 0]], np.int32)
    trace = eng.trace(7, max_steps=4000, faults=faults)
    fault_events = [e for e in trace if e["kind"].startswith("fault:")]
    assert [e["kind"] for e in fault_events] == ["fault:kill", "fault:restart"]
    assert fault_events[0]["t_us"] == 400_000
    # Events popped for the dead node between kill and restart are marked
    # dropped, never shown as handled.
    dead_window = [e for e in trace
                   if 400_000 < e["t_us"] < 800_000 and e["dst"] == 1
                   and not e["kind"].startswith("fault:")]
    assert dead_window, "some traffic addressed the dead node"
    assert all(e.get("dropped") for e in dead_window)


def test_queue_meta_packing_roundtrip():
    from madsim_tpu.engine.queue import pack_meta, unpack_meta

    # Full-width corners incl. gen=255 (sets the int32 sign bit packed).
    for kind, flags, src, dst, gen in [(0, 0, 0, 0, 0), (63, 3, 255, 255, 255),
                                       (7, 1, 3, 200, 128), (42, 2, 17, 0, 1)]:
        meta = pack_meta(jnp.int32(kind), jnp.int32(flags), jnp.int32(src),
                         jnp.int32(dst), jnp.int32(gen))
        k, f, s, d, g = (int(x) for x in unpack_meta(meta))
        assert (k, f, s, d, g) == (kind, flags, src, dst, gen)


def test_queue_inf_time_event_is_dropped_not_stored():
    q = empty_queue(2, 4)
    # An event at INF_TIME would alias the free-slot sentinel: it is
    # dropped at push (ok=True — it could never fire anyway) and consumes
    # no capacity.
    q, ok = push(q, Event.make(time=int(INF_TIME), kind=1, payload_words=4))
    assert bool(ok)
    q, ok1 = push(q, Event.make(time=5, kind=2, payload_words=4))
    q, ok2 = push(q, Event.make(time=6, kind=3, payload_words=4))
    assert bool(ok1) and bool(ok2)  # both real slots were still free
    q, ev, found = pop(q)
    assert bool(found) and int(ev.kind) == 2


def test_packed_width_guards(raft_engine):
    # Fault rows are validated at the init() boundary: the packed queue
    # stores node ids in 8 bits, so out-of-range ids must error rather
    # than alias onto a real node.
    with pytest.raises(ValueError, match="node ids"):
        raft_engine.init(np.arange(4),
                         faults=np.array([[1000, FAULT_KILL, 3, 0]], np.int32))
    with pytest.raises(ValueError, match="fault op"):
        raft_engine.init(np.arange(4),
                         faults=np.array([[1000, FAULT_RESUME + 1, 0, 0]],
                                         np.int32))
    # Disabled rows (time < 0) are exempt — ragged schedules pad with them.
    raft_engine.init(np.arange(4),
                     faults=np.array([[-1, 0, 99, 99]], np.int32))
    # Actors must declare num_kinds so the 6-bit kind guard has teeth.
    class NoKinds:
        pass

    with pytest.raises(ValueError, match="num_kinds"):
        DeviceEngine(NoKinds(), ECFG)


def test_per_world_config_grid_matches_per_config_compiles():
    """One compiled sweep over a (seeds × loss × latency) grid is bitwise
    identical to compiling one engine per config point (VERDICT r4 item 3:
    net config is world data, not a jit constant)."""
    rcfg = RaftDeviceConfig(n=3, n_proposals=1)
    seeds = np.arange(8, dtype=np.uint64)
    grid = [(1_000, 10_000, 0.0), (500, 2_000, 0.1), (2_000, 20_000, 0.3)]

    base = DeviceEngine(RaftActor(rcfg),
                        EngineConfig(n_nodes=3, outbox_cap=4,
                                     t_limit_us=4_000_000))
    all_seeds = np.tile(seeds, len(grid))
    configs = np.repeat(np.asarray(grid, np.float64), len(seeds), axis=0)
    obs_grid = base.observe(base.run(base.init(all_seeds, configs=configs),
                                     12_000))

    for gi, (lo, hi, p) in enumerate(grid):
        eng = DeviceEngine(RaftActor(rcfg),
                           EngineConfig(n_nodes=3, outbox_cap=4,
                                        t_limit_us=4_000_000,
                                        latency_min_us=lo, latency_max_us=hi,
                                        loss_rate=p))
        obs_one = eng.observe(eng.run(eng.init(seeds), 12_000))
        sl = slice(gi * len(seeds), (gi + 1) * len(seeds))
        for key, arr in obs_one.items():
            np.testing.assert_array_equal(
                np.asarray(obs_grid[key])[sl], np.asarray(arr),
                err_msg=f"config {gi} field {key} diverged from "
                        "the per-config compile")


def test_hot_loss_update_takes_effect_mid_run():
    """FAULT_SET_LOSS flips the network model at a virtual instant: total
    loss from t=0 prevents election entirely; lifting it at 1.5 s lets the
    same worlds elect afterwards (update_config parity, net/mod.rs:127-130)."""
    rcfg = RaftDeviceConfig(n=3)
    cfg = EngineConfig(n_nodes=3, outbox_cap=4, t_limit_us=3_000_000,
                       loss_rate=1.0)
    eng = DeviceEngine(RaftActor(rcfg), cfg)
    seeds = np.arange(8)

    obs_blocked = eng.observe(eng.run(eng.init(seeds), 12_000))
    assert not obs_blocked["leader_elected"].any()

    heal = np.array([[1_500_000, FAULT_SET_LOSS, 0, 0]], np.int32)
    obs_healed = eng.observe(eng.run(eng.init(seeds, faults=heal), 12_000))
    assert obs_healed["leader_elected"].all()
    assert not obs_healed["bug"].any()


def test_hot_latency_update_shifts_delivery_times():
    """FAULT_SET_LATENCY changes sampling bounds mid-run without a
    recompile: a world slowed to ~0.5 s per hop elects later than the
    default 1-10 ms world, under one compiled step."""
    rcfg = RaftDeviceConfig(n=3)
    # Electing needs timeout (>=150 ms) + vote request + response (2 hops):
    # at ~1 s per hop no world can elect inside 1.8 s; at the default
    # 1-10 ms every world does. Same compiled engine either way.
    cfg = EngineConfig(n_nodes=3, outbox_cap=4, t_limit_us=1_800_000)
    eng = DeviceEngine(RaftActor(rcfg), cfg)
    seeds = np.arange(8)

    slow = np.array([[0, FAULT_SET_LATENCY, 900_000, 1_100_000]], np.int32)
    obs_fast = eng.observe(eng.run(eng.init(seeds), 30_000))
    obs_slow = eng.observe(eng.run(eng.init(seeds, faults=slow), 30_000))
    assert obs_fast["leader_elected"].all()
    assert not obs_slow["leader_elected"].any()
    assert not obs_slow["bug"].any()


def test_config_validation_rejects_bad_grid():
    eng = DeviceEngine(RaftActor(RaftDeviceConfig(n=3)),
                       EngineConfig(n_nodes=3, outbox_cap=4))
    with pytest.raises(ValueError, match="latency_min"):
        eng.init(np.arange(2), configs=np.array([10.0, 5.0, 0.0]))
    with pytest.raises(ValueError, match="loss_rate"):
        eng.init(np.arange(2), configs=np.array([1.0, 10.0, 1.5]))
    with pytest.raises(ValueError, match="SET_LOSS"):
        eng.init(np.arange(2),
                 faults=np.array([[0, 7, 2_000_000, 0]], np.int32))


def test_pause_buffers_deliveries_and_reelects():
    """Device pause/resume (VERDICT r4 item 5): pausing node 0 past the
    election timeout re-elects in worlds it led; deliveries during the
    pause are BUFFERED and flush on resume (vs kill, which drops); the
    resumed stale leader steps down (at most one leader everywhere)."""
    from madsim_tpu.engine.raft_actor import LEADER

    cfg = EngineConfig(n_nodes=3, outbox_cap=4, t_limit_us=3_000_000)
    eng = DeviceEngine(RaftActor(RaftDeviceConfig(n=3)), cfg)
    seeds = np.arange(64)

    pause = np.array([[400_000, FAULT_PAUSE, 0, 0],
                      [1_200_000, FAULT_RESUME, 0, 0]], np.int32)
    st_p = eng.run(eng.init(seeds, faults=pause), 12_000)
    obs_p = eng.observe(st_p)
    assert obs_p["leader_elected"].all()
    assert not obs_p["bug"].any()
    assert (obs_p["elections_won"] >= 2).any()  # node 0 led somewhere: re-elect
    roles = np.asarray(st_p.astate.role)
    assert ((roles == LEADER).sum(axis=1) <= 1).all(), \
        "a stale leader survived resume without stepping down"

    # Same window as a kill: messages to the dead node are popped-and-
    # dropped, while the pause defers them — so the pause run must drop
    # strictly less on average.
    kill = np.array([[400_000, FAULT_KILL, 0, 0],
                     [1_200_000, FAULT_RESTART, 0, 0]], np.int32)
    obs_k = eng.observe(eng.run(eng.init(seeds, faults=kill), 12_000))
    assert obs_p["dropped"].mean() < obs_k["dropped"].mean()


def test_pause_without_resume_freezes_world_cleanly():
    """All remaining events ineligible (paused dst, no resume scheduled) is
    the device's deadlock analog: the world freezes inactive, no bug."""
    cfg = EngineConfig(n_nodes=3, outbox_cap=4, t_limit_us=2_000_000)
    eng = DeviceEngine(RaftActor(RaftDeviceConfig(n=3)), cfg)
    faults = np.array([[0, FAULT_PAUSE, 0, 0],
                       [0, FAULT_PAUSE, 1, 0],
                       [0, FAULT_PAUSE, 2, 0]], np.int32)
    obs = eng.observe(eng.run(eng.init(np.arange(4), faults=faults), 4_000))
    assert not obs["active"].any()
    assert not obs["bug"].any()
    assert not obs["leader_elected"].any()  # nothing ever ran
