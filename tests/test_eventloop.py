"""Event-loop-level drop-in: unmodified third-party libraries that open
their own sockets through the running loop run in-sim (VERDICT r4 item 2).

The flagship proof mirrors the reference's tokio-postgres demonstration
(`madsim-tokio-postgres/src/socket.rs:6-13`: upstream code, sim sockets):
pip-installed aiohttp — client *and* server, ~40 kLoC of third-party
asyncio code — runs over the simulated network with no source changes,
under partition chaos and node restarts, bit-identically across same-seed
runs.
"""
import asyncio

import pytest

import madsim_tpu as ms
from madsim_tpu import time as vtime
from madsim_tpu.core.futures import SimFuture
from madsim_tpu.net import NetSim
from madsim_tpu.shims import aio

aiohttp = pytest.importorskip("aiohttp")
from aiohttp import web  # noqa: E402


def run_world(world_fn, seed):
    with aio.patched():
        rt = ms.Runtime(seed=seed)
        tr = []
        rt.task.trace = tr
        value = rt.block_on(world_fn())
        return value, tr


# ---------------------------------------------------------------------------
# Raw transport/protocol surface
# ---------------------------------------------------------------------------

class _EchoServer(asyncio.Protocol):
    def connection_made(self, transport):
        self.transport = transport

    def data_received(self, data):
        self.transport.write(b"echo:" + data)


class _Client(asyncio.Protocol):
    def __init__(self, fut):
        self.fut = fut
        self.buf = b""

    def connection_made(self, transport):
        transport.write(b"hello")

    def data_received(self, data):
        self.buf += data
        if self.buf.endswith(b"hello"):
            self.fut.set_result(self.buf)


def test_create_connection_create_server_roundtrip():
    async def world():
        h = ms.Handle.current()

        async def server_init():
            loop = asyncio.get_running_loop()
            server = await loop.create_server(_EchoServer, "10.0.0.1", 9000)
            assert server.sockets[0].getsockname() == ("10.0.0.1", 9000)
            await vtime.sleep(1e6)

        h.create_node(name="srv", ip="10.0.0.1", init=server_init)
        cli = h.create_node(name="cli", ip="10.0.0.2")

        async def client():
            await vtime.sleep(0.1)
            loop = asyncio.get_running_loop()
            fut = SimFuture()
            tr, _proto = await loop.create_connection(
                lambda: _Client(fut), "10.0.0.1", 9000)
            data = await fut
            tr.close()
            return data

        return await cli.spawn(client())

    value, _ = run_world(world, 3)
    assert value == b"echo:hello"


def test_sock_connect_sendall_recv():
    """The raw-socket surface modern clients use (aiohappyeyeballs path):
    a real socket object as the token for a sim stream."""
    import socket

    async def world():
        h = ms.Handle.current()

        async def server_init():
            loop = asyncio.get_running_loop()
            await loop.create_server(_EchoServer, "10.0.0.1", 9100)
            await vtime.sleep(1e6)

        h.create_node(name="srv", ip="10.0.0.1", init=server_init)
        cli = h.create_node(name="cli", ip="10.0.0.2")

        async def client():
            await vtime.sleep(0.1)
            loop = asyncio.get_running_loop()
            infos = await loop.getaddrinfo("10.0.0.1", 9100,
                                           type=socket.SOCK_STREAM)
            family, type_, proto, _cname, addr = infos[0]
            sock = socket.socket(family, type_, proto)
            try:
                sock.setblocking(False)
                await loop.sock_connect(sock, addr)
                await loop.sock_sendall(sock, b"ping")
                data = await loop.sock_recv(sock, 1024)
            finally:
                sock.close()
            return data

        return await cli.spawn(client())

    value, _ = run_world(world, 4)
    assert value == b"echo:ping"


# ---------------------------------------------------------------------------
# aiohttp, unmodified
# ---------------------------------------------------------------------------

def _aiohttp_world(requests=5, chaos=False, restart=False):
    """Server node runs an unmodified aiohttp web app; client node drives
    an unmodified ClientSession with retries; optional partition chaos and
    server restarts."""

    async def world():
        h = ms.Handle.current()

        async def server_init():
            app = web.Application()

            async def echo(request):
                body = await request.read()
                return web.Response(body=body)

            app.router.add_post("/echo", echo)
            runner = web.AppRunner(app)
            await runner.setup()
            site = web.TCPSite(runner, "10.0.0.1", 8080)
            await site.start()
            await vtime.sleep(1e6)

        srv = h.create_node(name="srv", ip="10.0.0.1", init=server_init)
        cli = h.create_node(name="cli", ip="10.0.0.2")

        async def client():
            await vtime.sleep(0.5)
            log = []
            # Total timeout below the partition window so a stalled request
            # *fails* (and retries) instead of merely arriving late.
            timeout = aiohttp.ClientTimeout(total=0.8)
            async with aiohttp.ClientSession(timeout=timeout) as sess:
                for i in range(requests):
                    if chaos or restart:
                        await vtime.sleep(0.5)  # spread across chaos windows
                    body = f"msg-{i}".encode()
                    attempts = 0
                    while True:
                        attempts += 1
                        try:
                            async with sess.post(
                                    "http://10.0.0.1:8080/echo",
                                    data=body) as resp:
                                assert resp.status == 200
                                got = await resp.read()
                                assert got == body, (got, body)
                            break
                        except (aiohttp.ClientError, asyncio.TimeoutError,
                                ConnectionError, TimeoutError):
                            await vtime.sleep(0.25)
                    log.append((i, attempts))
            return log

        t = cli.spawn(client())

        if chaos or restart:
            async def chaos_task():
                sim = ms.simulator(NetSim)
                for round_ in range(3):
                    await vtime.sleep(0.9)
                    if chaos:
                        sim.disconnect2(srv.id, cli.id)
                        await vtime.sleep(1.2)
                        sim.connect2(srv.id, cli.id)
                    if restart:
                        h.restart(srv)
                        await vtime.sleep(0.4)

            from madsim_tpu import task as mtask

            mtask.spawn(chaos_task())

        return await t

    return world


def test_aiohttp_echo_roundtrips():
    value, _ = run_world(_aiohttp_world(requests=5), 11)
    assert [i for i, _a in value] == list(range(5))
    assert all(a >= 1 for _i, a in value)


def test_aiohttp_under_partition_chaos_deterministic():
    """Partitions stall/kill in-flight requests; retries make progress; and
    the whole thing — aiohttp internals included — replays bit-identically
    from the seed."""
    world = _aiohttp_world(requests=6, chaos=True)
    v1, t1 = run_world(world, 1234)
    v2, t2 = run_world(world, 1234)
    assert [i for i, _a in v1] == list(range(6))
    assert v1 == v2
    assert t1 == t2, "aiohttp world diverged across same-seed runs"
    # Chaos must actually have caused retries somewhere, or the partition
    # windows never intersected a request and the test is vacuous.
    assert any(a > 1 for _i, a in v1), v1


def test_aiohttp_survives_server_restart():
    """Node restart resets the server (connections die, aiohttp re-binds
    via the init closure); the unmodified client reconnects and completes."""
    world = _aiohttp_world(requests=6, restart=True)
    v1, t1 = run_world(world, 7)
    v2, t2 = run_world(world, 7)
    assert [i for i, _a in v1] == list(range(6))
    assert (v1, t1) == (v2, t2)


def test_patched_asyncio_task_remains_a_type():
    """asyncio.Task is patched for in-sim construction but must remain a
    real type: isinstance checks and subclassing (both common in async
    libraries) keep working, in and out of sim."""
    with aio.patched():
        assert isinstance(asyncio.Task, type)

        class MyTask(asyncio.Task):  # subclassing must not explode
            pass

        async def coro():
            return 1

        # outside a sim, construction falls through to the real class on a
        # real running loop.
        async def real_world():
            t = asyncio.Task(coro())
            assert isinstance(t, asyncio.Task)
            return await t

        assert asyncio.run(real_world()) == 1

        # in-sim construction returns a sim task (the aiohttp 3.12
        # eager_start call shape), and isinstance sees sim tasks.
        async def world():
            t = asyncio.Task(coro(), eager_start=True)
            assert isinstance(t, asyncio.Task)
            return await t

        rt = ms.Runtime(seed=5)
        assert rt.block_on(world()) == 1


def test_unmodified_websockets_library_in_sim():
    """pip `websockets` (Sans-I/O core + asyncio integration, stdlib
    asyncio.timeout bound at import time, keepalive ping timers): client
    and server run unmodified over the sim network, deterministically."""
    websockets = pytest.importorskip("websockets")
    from websockets.asyncio.client import connect
    from websockets.asyncio.server import serve

    async def world():
        h = ms.Handle.current()

        async def server_init():
            async def echo(ws):
                async for msg in ws:
                    await ws.send(f"echo:{msg}")

            # No `async with`: the world outlives the test body, and the
            # context manager's GC-time __aexit__ would suspend (awaiting
            # websockets' close machinery) — abandoned servers are simply
            # dropped with their world, like every other sim resource.
            await serve(echo, "10.0.0.1", 8765)
            await vtime.sleep(1e6)

        h.create_node(name="srv", ip="10.0.0.1", init=server_init)
        cli = h.create_node(name="cli", ip="10.0.0.2")

        async def client():
            await vtime.sleep(0.3)
            out = []
            async with connect("ws://10.0.0.1:8765") as ws:
                for i in range(5):
                    await ws.send(f"m{i}")
                    out.append(await ws.recv())
            return out

        return await cli.spawn(client())

    v1, t1 = run_world(world, 21)
    v2, t2 = run_world(world, 21)
    assert v1 == [f"echo:m{i}" for i in range(5)]
    assert (v1, t1) == (v2, t2)


def test_unmodified_httpx_client_in_sim():
    """pip `httpx` (anyio structured concurrency, task-state registries
    keyed by weakref'd current task, socket extras introspection) talks to
    an unmodified aiohttp server in-sim, deterministically."""
    httpx = pytest.importorskip("httpx")

    async def world():
        h = ms.Handle.current()

        async def server_init():
            app = web.Application()

            async def hello(request):
                return web.json_response({"n": int(request.query["n"])})

            app.router.add_get("/hello", hello)
            runner = web.AppRunner(app)
            await runner.setup()
            await web.TCPSite(runner, "10.0.0.1", 80).start()
            await vtime.sleep(1e6)

        h.create_node(name="srv", ip="10.0.0.1", init=server_init)
        cli = h.create_node(name="cli", ip="10.0.0.2")

        async def client():
            await vtime.sleep(0.3)
            out = []
            async with httpx.AsyncClient() as c:
                for i in range(4):
                    r = await c.get(f"http://10.0.0.1/hello?n={i}")
                    assert r.status_code == 200
                    out.append(r.json()["n"])
            return out

        return await cli.spawn(client())

    v1, t1 = run_world(world, 31)
    v2, t2 = run_world(world, 31)
    assert v1 == [0, 1, 2, 3]
    assert (v1, t1) == (v2, t2)


def test_aiohttp_world_sweeps_through_bridge_bit_identically():
    """Feature composition: an event-loop drop-in world (unmodified
    aiohttp) swept through the DEVICE BRIDGE walks the host engine's
    bit-identical trajectory per seed — the loop's timers and transports
    ride BridgeTime's device-resident lanes with no special casing."""
    from madsim_tpu.bridge import sweep_traced

    def make_world():
        async def world():
            h = ms.Handle.current()

            async def server_init():
                app = web.Application()

                async def echo(request):
                    return web.Response(body=await request.read())

                app.router.add_post("/e", echo)
                runner = web.AppRunner(app)
                await runner.setup()
                await web.TCPSite(runner, "10.0.0.1", 80).start()
                await vtime.sleep(1e6)

            h.create_node(name="s", ip="10.0.0.1", init=server_init)
            cli = h.create_node(name="c", ip="10.0.0.2")

            async def client():
                await vtime.sleep(0.2)
                n = 0
                async with aiohttp.ClientSession() as sess:
                    for i in range(3):
                        async with sess.post("http://10.0.0.1/e",
                                             data=b"x" * i) as r:
                            assert r.status == 200
                            n += len(await r.read())
                return n

            return await cli.spawn(client())

        return world

    with aio.patched():
        host = []
        for seed in (3, 4):
            rt = ms.Runtime(seed=seed)
            tr = []
            rt.task.trace = tr
            host.append((rt.block_on(make_world()()), tr))
        outs, trs = sweep_traced(make_world(), [3, 4])
    for i in range(2):
        assert outs[i].error is None, outs[i].error
        assert outs[i].value == host[i][0] == 3
        assert trs[i] == host[i][1], f"world {i} diverged from host"


def test_create_datagram_endpoint_udp_roundtrip():
    """The datagram loop surface (DNS-resolver/UDP-library shape):
    DatagramProtocol server + connected client over sim UDP,
    deterministic across same-seed runs."""

    class EchoUdp(asyncio.DatagramProtocol):
        def connection_made(self, transport):
            self.transport = transport

        def datagram_received(self, data, addr):
            self.transport.sendto(b"ack:" + data, addr)

    class ClientUdp(asyncio.DatagramProtocol):
        def __init__(self, fut, want):
            self.fut = fut
            self.want = want
            self.got = []

        def connection_made(self, transport):
            self.transport = transport

        def datagram_received(self, data, addr):
            self.got.append(data)
            if len(self.got) == self.want:
                self.fut.set_result(self.got)

    async def world():
        h = ms.Handle.current()

        async def server_init():
            loop = asyncio.get_running_loop()
            await loop.create_datagram_endpoint(
                EchoUdp, local_addr=("10.0.0.1", 5353))
            await vtime.sleep(1e6)

        h.create_node(name="srv", ip="10.0.0.1", init=server_init)
        cli = h.create_node(name="cli", ip="10.0.0.2")

        async def client():
            await vtime.sleep(0.2)
            loop = asyncio.get_running_loop()
            fut = SimFuture()
            tr, _proto = await loop.create_datagram_endpoint(
                lambda: ClientUdp(fut, 3),
                remote_addr=("10.0.0.1", 5353))
            for i in range(3):
                tr.sendto(f"d{i}".encode())
            got = await fut
            tr.close()
            return got

        return await cli.spawn(client())

    v1, t1 = run_world(world, 17)
    v2, t2 = run_world(world, 17)
    assert v1 == [b"ack:d0", b"ack:d1", b"ack:d2"]
    assert (v1, t1) == (v2, t2)


def test_stdlib_asyncio_streams_over_sim_loop():
    """`asyncio.open_connection` / `start_server` — the StreamReader/
    StreamWriter API most libraries reach for — runs over the sim loop
    with no special casing: the stdlib's StreamReaderProtocol machinery
    sits on create_connection/create_server + create_future/call_soon,
    all of which the SimEventLoop provides. Deterministic across runs."""

    async def world():
        h = ms.Handle.current()

        async def srv():
            async def on_client(reader, writer):
                while True:
                    line = await reader.readline()
                    if not line:
                        break
                    writer.write(b"echo:" + line)
                    await writer.drain()
                writer.close()

            await asyncio.start_server(on_client, "10.0.0.1", 7000)
            await vtime.sleep(1e6)

        h.create_node(name="s", ip="10.0.0.1", init=srv)
        c = h.create_node(name="c", ip="10.0.0.2")

        async def client():
            await vtime.sleep(0.2)
            reader, writer = await asyncio.open_connection("10.0.0.1", 7000)
            out = []
            for i in range(3):
                writer.write(f"m{i}\n".encode())
                await writer.drain()
                out.append(await reader.readline())
            writer.close()
            # Half-close from our side: the server loop reads EOF, echoes
            # nothing more, and closes; our reader then sees EOF too.
            assert await reader.read() == b""
            return out

        return await c.spawn(client())

    v1, t1 = run_world(world, 23)
    v2, t2 = run_world(world, 23)
    assert v1 == [b"echo:m0\n", b"echo:m1\n", b"echo:m2\n"]
    assert (v1, t1) == (v2, t2)


def test_bare_none_yield_reschedules_like_stdlib_task():
    """Hand-rolled awaitables that do a bare ``yield`` (aiohttp's
    helpers.noop, stdlib __sleep0-style) mean "resume me next loop turn"
    under asyncio's Task; the sim maps that to the yield_now scheduling
    point — on both the native and Python poll loops."""

    class BareYield:
        def __await__(self):
            yield

    async def world():
        order = []

        async def other():
            order.append("other")

        from madsim_tpu import task as mtask

        mtask.spawn(other())
        await BareYield()  # suspends exactly one scheduling turn
        order.append("me")
        return order

    for force_python in (False, True):
        rt = ms.Runtime(seed=2)
        if force_python:
            rt.task._native_ready = None
        assert rt.block_on(world()) == ["other", "me"]


def test_aiohttp_websocket_heartbeats_on_virtual_time():
    """aiohttp's own websocket layer with 1 s heartbeats: pings, pongs,
    and the pong-timeout timers all ride virtual time across a 5 s quiet
    window (both peers idling in their receive loops, the realistic ws
    shape — pong processing lives in receive(), same as real asyncio)."""

    async def world():
        h = ms.Handle.current()

        async def srv():
            async def ws_handler(request):
                ws = web.WebSocketResponse(heartbeat=1.0)
                await ws.prepare(request)

                async def pusher():
                    await ws.send_str("hello")
                    await vtime.sleep(5.0)
                    await ws.send_str("still-here")

                task = asyncio.create_task(pusher())
                async for _msg in ws:
                    pass
                task.cancel()
                return ws

            app = web.Application()
            app.router.add_get("/ws", ws_handler)
            runner = web.AppRunner(app)
            await runner.setup()
            await web.TCPSite(runner, "10.0.0.1", 80).start()
            await vtime.sleep(1e6)

        h.create_node(name="s", ip="10.0.0.1", init=srv)
        c = h.create_node(name="c", ip="10.0.0.2")

        async def client():
            await vtime.sleep(0.2)
            out = []
            async with aiohttp.ClientSession() as sess:
                async with sess.ws_connect("http://10.0.0.1/ws",
                                           heartbeat=1.0) as ws:
                    out.append((await ws.receive()).data)
                    out.append((await ws.receive()).data)
            return out

        return await c.spawn(client())

    v1, t1 = run_world(world, 13)
    v2, t2 = run_world(world, 13)
    assert v1 == ["hello", "still-here"]
    assert (v1, t1) == (v2, t2)


def test_bare_yield_spinner_cannot_starve_timers_or_time_limit():
    """A loop spin-waiting on bare yields for a timer-driven event must see
    the timer fire (the drain path delivers due timers), and a spinner with
    no timers must still hit the time limit instead of hanging — on both
    poll loops."""

    class BareYield:
        def __await__(self):
            yield

    async def timer_world():
        from madsim_tpu import task as mtask

        fired = []
        ms.Handle.current().time.add_timer(1_000_000,  # 1 ms
                                           lambda: fired.append(True))
        spins = 0
        while not fired:
            await BareYield()
            spins += 1
            assert spins < 200_000, "timer starved by yield spinning"
        return spins

    async def endless_spinner():
        while True:
            await BareYield()

    for force_python in (False, True):
        rt = ms.Runtime(seed=3)
        if force_python:
            rt.task._native_ready = None
        spins = rt.block_on(timer_world())
        assert spins > 1000  # virtual time advanced by poll jitter to 1 ms

        rt = ms.Runtime(seed=3)
        if force_python:
            rt.task._native_ready = None
        rt.set_time_limit(0.01)
        from madsim_tpu.core.task import TimeLimitExceeded

        with pytest.raises(TimeLimitExceeded):
            rt.block_on(endless_spinner())
