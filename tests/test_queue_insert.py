"""Single-pass outbox insertion (queue.push_many), the carried queue-depth
lane, buffer donation, and the per-step op budget (PR "Single-pass outbox
insertion, incremental queue depth, and donated step buffers").

The load-bearing contract: ``push_many`` (and the engine built on it) is
**bitwise identical** to the statically unrolled sequential push chain it
replaced. The sequential path is kept alive behind
``EngineConfig(sequential_insert=True)`` precisely so these tests can run
whole trajectories both ways and compare every state leaf.
"""
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from madsim_tpu.engine import (
    DeviceEngine, EngineConfig, RaftActor, RaftDeviceConfig,
    PBActor, PBDeviceConfig, TPCActor, TPCDeviceConfig,
    FAULT_KILL, FAULT_PAUSE, FAULT_RESTART, FAULT_SET_LATENCY, INF_TIME,
)
from madsim_tpu.engine.queue import (
    Event, depth, empty_queue, pop, pop_indexed, push, push_many,
)


def _random_events(rng, m, p):
    times = rng.integers(0, 120, m)
    # INF_TIME events must be dropped without consuming a slot.
    times = np.where(rng.random(m) < 0.2, int(INF_TIME), times)
    return Event(
        time=jnp.asarray(times, jnp.int32),
        kind=jnp.asarray(rng.integers(0, 6, m), jnp.int32),
        flags=jnp.asarray(rng.integers(0, 4, m), jnp.int32),
        src=jnp.asarray(rng.integers(0, 4, m), jnp.int32),
        dst=jnp.asarray(rng.integers(0, 4, m), jnp.int32),
        gen=jnp.asarray(rng.integers(0, 256, m), jnp.int32),
        payload=jnp.asarray(rng.integers(0, 1000, (m, p)), jnp.int32),
    )


def _push_sequentially(q, evs, enable):
    oks = []
    for i in range(evs.time.shape[0]):
        ev = Event(time=evs.time[i], kind=evs.kind[i], flags=evs.flags[i],
                   src=evs.src[i], dst=evs.dst[i], gen=evs.gen[i],
                   payload=evs.payload[i])
        q, ok = push(q, ev, enable=bool(enable[i]))
        oks.append(bool(ok))
    return q, oks


def _queues_equal(a, b):
    return (np.array_equal(a.time, b.time) and np.array_equal(a.meta, b.meta)
            and np.array_equal(a.payload, b.payload))


# ---------------------------------------------------------------------------
# Queue-level equivalence: push_many == the sequential push chain
# ---------------------------------------------------------------------------

def test_push_many_matches_sequential_chain_randomized():
    """Randomized queues (pre-filled, holey after pops) x event batches
    (INF times, disabled slots, more events than capacity): the fused
    insert must reproduce the chain's slot assignment, ok flags, and
    inserted count exactly."""
    rng = np.random.default_rng(0)
    for trial in range(60):
        cap = int(rng.integers(2, 70))
        m = int(rng.integers(1, 9))
        p = int(rng.integers(1, 5))
        q = empty_queue(cap, p)
        for _ in range(int(rng.integers(0, cap + 1))):
            q, _ = push(q, Event.make(time=int(rng.integers(0, 50)),
                                      kind=int(rng.integers(0, 6)),
                                      payload_words=p))
        for _ in range(int(rng.integers(0, 5))):  # punch holes
            q, _, _ = pop(q)
        evs = _random_events(rng, m, p)
        enable = rng.random(m) < 0.8
        q_seq, oks = _push_sequentially(q, evs, enable)
        q_fused, ok_f, n_ins = push_many(q, evs, jnp.asarray(enable))
        assert _queues_equal(q_seq, q_fused), f"trial {trial}"
        assert oks == [bool(x) for x in ok_f], f"trial {trial}"
        assert int(depth(q_fused)) - int(depth(q)) == int(n_ins), f"trial {trial}"


def test_push_many_overflow_mid_batch():
    """More enabled events than free slots: the first n_free (in event
    order) land, the rest report ok=False and write nothing."""
    q = empty_queue(4, 2)
    q, _ = push(q, Event.make(time=5, kind=1, payload_words=2))
    q, _ = push(q, Event.make(time=6, kind=2, payload_words=2))
    evs = Event(time=jnp.asarray([10, 11, 12, 13], jnp.int32),
                kind=jnp.asarray([7, 8, 9, 10], jnp.int32),
                flags=jnp.zeros((4,), jnp.int32), src=jnp.zeros((4,), jnp.int32),
                dst=jnp.zeros((4,), jnp.int32), gen=jnp.zeros((4,), jnp.int32),
                payload=jnp.zeros((4, 2), jnp.int32))
    q2, ok, n_ins = push_many(q, evs)
    assert [bool(x) for x in ok] == [True, True, False, False]
    assert int(n_ins) == 2
    assert int(depth(q2)) == 4
    q_seq, oks = _push_sequentially(q, evs, np.ones(4, bool))
    assert _queues_equal(q_seq, q2) and oks == [True, True, False, False]


def test_push_many_inf_time_dropped_without_slot():
    q = empty_queue(2, 2)
    evs = Event(time=jnp.asarray([int(INF_TIME), 7, 8], jnp.int32),
                kind=jnp.asarray([1, 2, 3], jnp.int32),
                flags=jnp.zeros((3,), jnp.int32), src=jnp.zeros((3,), jnp.int32),
                dst=jnp.zeros((3,), jnp.int32), gen=jnp.zeros((3,), jnp.int32),
                payload=jnp.zeros((3, 2), jnp.int32))
    q2, ok, n_ins = push_many(q, evs)
    # The INF event is dropped ok=True and the two real events still fit.
    assert [bool(x) for x in ok] == [True, True, True]
    assert int(n_ins) == 2
    _, ev, found = pop(q2)
    assert bool(found) and int(ev.kind) == 2


def test_push_many_clear_fuses_the_pop():
    """push_many(q, ..., clear=(slot, found)) == pop the slot first, then
    push — including the popped slot being immediately reusable."""
    rng = np.random.default_rng(1)
    for trial in range(40):
        cap = int(rng.integers(2, 20))
        p = int(rng.integers(1, 4))
        q = empty_queue(cap, p)
        for _ in range(int(rng.integers(0, cap + 1))):
            q, _ = push(q, Event.make(time=int(rng.integers(0, 50)),
                                      kind=int(rng.integers(0, 6)),
                                      payload_words=p))
        m = int(rng.integers(1, 6))
        evs = _random_events(rng, m, p)
        enable = jnp.asarray(rng.random(m) < 0.8)
        q_pop, _ev, found, slot = pop_indexed(q)
        a, ok_a, n_a = push_many(q_pop, evs, enable)
        b, ok_b, n_b = push_many(q, evs, enable, clear=(slot, found))
        assert _queues_equal(a, b), f"trial {trial}"
        assert np.array_equal(ok_a, ok_b) and int(n_a) == int(n_b)


# ---------------------------------------------------------------------------
# Engine-level equivalence: whole trajectories, all three actor families
# ---------------------------------------------------------------------------

def _leaves_bitwise_equal(a, b):
    mismatched = []
    paths = [jax.tree_util.keystr(pth)
             for pth, _ in jax.tree_util.tree_flatten_with_path(a)[0]]
    for path, x, y in zip(paths, jax.tree.leaves(a), jax.tree.leaves(b)):
        if not np.array_equal(np.asarray(x), np.asarray(y)):
            mismatched.append(path)
    return mismatched


def _run_both_ways(actor, cfg, seeds, faults=None, max_steps=5_000):
    fused = DeviceEngine(actor, cfg)
    seq = DeviceEngine(actor, dataclasses.replace(cfg, sequential_insert=True))
    sf = fused.run(fused.init(seeds, faults=faults), max_steps)
    ss = seq.run(seq.init(seeds, faults=faults), max_steps)
    mism = _leaves_bitwise_equal(sf, ss)
    assert not mism, f"fused vs sequential diverged on: {mism}"
    return fused, sf


def test_raft_trajectories_bitwise_equal_incl_faults():
    actor = RaftActor(RaftDeviceConfig(n=3, n_proposals=2,
                                       buggy_double_vote=True))
    cfg = EngineConfig(n_nodes=3, outbox_cap=4, queue_cap=64,
                       t_limit_us=2_500_000, stop_on_bug=False)
    faults = np.array([[400_000, FAULT_KILL, 0, 0],
                       [900_000, FAULT_RESTART, 0, 0]], np.int32)
    _run_both_ways(actor, cfg, np.arange(48), faults=faults)


def test_raft_overflow_mid_batch_bitwise_equal():
    """A queue too small for the traffic: worlds overflow mid-outbox
    (some of a handler's sends land, the rest drop) and the two engines
    must still agree bitwise — including the overflow flag."""
    actor = RaftActor(RaftDeviceConfig(n=3, n_proposals=2))
    cfg = EngineConfig(n_nodes=3, outbox_cap=4, queue_cap=8,
                       t_limit_us=2_000_000, stop_on_bug=False)
    eng, state = _run_both_ways(actor, cfg, np.arange(48))
    assert eng.observe(state)["overflow"].any(), (
        "config failed to overflow — the overflow-mid-batch path went "
        "unexercised; shrink queue_cap")


def test_raft_inf_saturated_sends_bitwise_equal():
    """Latency hot-set near int32 max: deliveries at ~2e9 µs make the
    *next* hop saturate to INF_TIME and drop at push. Both engines must
    drop identically."""
    actor = RaftActor(RaftDeviceConfig(n=3))
    cfg = EngineConfig(n_nodes=3, outbox_cap=4, queue_cap=64,
                       t_limit_us=2**31 - 2, stop_on_bug=False)
    slow = np.array([[0, FAULT_SET_LATENCY, 2_000_000_000, 2_147_483_646]],
                    np.int32)
    _run_both_ways(actor, cfg, np.arange(16), faults=slow, max_steps=2_000)


def test_raft_pause_all_ineligible_pops_bitwise_equal():
    """Every node paused, nothing ever eligible: pop finds nothing on a
    non-empty queue, worlds freeze — identically in both engines."""
    actor = RaftActor(RaftDeviceConfig(n=3))
    cfg = EngineConfig(n_nodes=3, outbox_cap=4, queue_cap=64,
                       t_limit_us=2_000_000)
    faults = np.array([[0, FAULT_PAUSE, 0, 0],
                       [0, FAULT_PAUSE, 1, 0],
                       [0, FAULT_PAUSE, 2, 0]], np.int32)
    eng, state = _run_both_ways(actor, cfg, np.arange(8), faults=faults,
                                max_steps=2_000)
    obs = eng.observe(state)
    assert not obs["active"].any() and not obs["bug"].any()
    assert (obs["queue_depth"] > 0).all()  # frozen with buffered events


def test_pb_trajectories_bitwise_equal():
    actor = PBActor(PBDeviceConfig(n=3, n_writes=4))
    cfg = EngineConfig(n_nodes=3, outbox_cap=4, queue_cap=64,
                       t_limit_us=1_500_000, loss_rate=0.05)
    _run_both_ways(actor, cfg, np.arange(48))


def test_tpc_trajectories_bitwise_equal():
    actor = TPCActor(TPCDeviceConfig(n=4, n_txns=4,
                                     buggy_presumed_commit=True))
    cfg = EngineConfig(n_nodes=4, outbox_cap=5, queue_cap=64,
                       t_limit_us=1_500_000, loss_rate=0.1)
    _run_both_ways(actor, cfg, np.arange(48))


# ---------------------------------------------------------------------------
# The carried depth lane
# ---------------------------------------------------------------------------

def test_carried_depth_equals_recomputed_reduction():
    """WorldState.qdepth (maintained incrementally by pop/push_many) must
    equal the O(Q) recomputed reduction at every observation point, over
    mixed push/pop/overflow/pause trajectories."""
    configs = [
        # overflow-heavy (tiny queue), clean, and pause-buffered worlds
        (RaftActor(RaftDeviceConfig(n=3, n_proposals=2)),
         EngineConfig(n_nodes=3, outbox_cap=4, queue_cap=8,
                      t_limit_us=2_000_000, stop_on_bug=False), None),
        (RaftActor(RaftDeviceConfig(n=3, buggy_double_vote=True)),
         EngineConfig(n_nodes=3, outbox_cap=4, queue_cap=64,
                      t_limit_us=2_000_000), None),
        (RaftActor(RaftDeviceConfig(n=3)),
         EngineConfig(n_nodes=3, outbox_cap=4, queue_cap=64,
                      t_limit_us=2_000_000),
         np.array([[100_000, FAULT_PAUSE, 0, 0],
                   [500_000, FAULT_KILL, 1, 0]], np.int32)),
    ]
    for actor, cfg, faults in configs:
        eng = DeviceEngine(actor, cfg)
        state = eng.init(np.arange(32), faults=faults)
        for _ in range(6):  # several mid-run checkpoints, not just the end
            state = eng.run_steps(state, 100)
            carried = np.asarray(state.qdepth)
            recomputed = np.asarray(jax.vmap(depth)(state.queue))
            np.testing.assert_array_equal(carried, recomputed)
        # qmax is the high-water mark of the carried value.
        assert (np.asarray(state.qmax) >= np.asarray(state.qdepth)).all()
        assert (np.asarray(eng.observe(state)["queue_depth"])
                == recomputed).all()


# ---------------------------------------------------------------------------
# Op budget + donated memory (the two tier-1 regression gates)
# ---------------------------------------------------------------------------

# Cost-model flops per world-step for the time_to_first_bug engine config
# (3-node, queue_cap=64), measured via compiled.cost_analysis() on the CPU
# backend. Measured 7727 after the single-pass insert landed (the
# pre-rewrite step measured 21469 — a 2.8x reduction). The budget now
# lives in the checked-in ledger `madsim_tpu/analysis/budgets.json`
# (engine.run entry) — ONE source of truth shared with `make tracelint`
# — regenerated via `tools/update_budgets.py --reason '...'` IN THE SAME
# PR as any change that legitimately alters the step's op count, with
# the new measurement in docs/perf.md.
from madsim_tpu.analysis import budgets as _budgets

_LEDGER = _budgets.load_ledger()
FLOPS_PER_WORLD_STEP_BUDGET = _budgets.budget_for(
    _LEDGER, "engine.run", "flops_per_world")
PEAK_OVER_STATE_BUDGET = _budgets.budget_for(
    _LEDGER, "engine.run", "peak_over_arg")
assert FLOPS_PER_WORLD_STEP_BUDGET and PEAK_OVER_STATE_BUDGET, (
    "analysis/budgets.json lost its engine.run budgets — regenerate via "
    "tools/update_budgets.py")


def _bug_config_engine():
    rcfg = RaftDeviceConfig(n=3, buggy_double_vote=True)
    cfg = EngineConfig(n_nodes=3, outbox_cap=4, queue_cap=64,
                       t_limit_us=2_000_000, stop_on_bug=False)
    return DeviceEngine(RaftActor(rcfg), cfg)


# Compile BYPASSING the persistent compilation cache (conftest.py): an
# executable deserialized from the cache loses parts of its cost/memory
# statistics (alias_size_in_bytes reads 0), which would let the budget
# gates below silently pass-or-fail on cache state instead of on the
# program. The shared implementation lives in analysis/budgets.py, next
# to the ledger the measurements feed.
_compile_fresh = _budgets.compile_fresh


def test_step_op_budget_regression():
    eng = _bug_config_engine()
    w = 256
    state = eng.init(np.arange(w))
    comp = _compile_fresh(eng._run.lower(state, 4_000))
    ca = comp.cost_analysis()
    if isinstance(ca, (list, tuple)):  # older jax returns [dict]
        ca = ca[0]
    per_world = float(ca["flops"]) / w
    assert per_world <= FLOPS_PER_WORLD_STEP_BUDGET, (
        f"step costs {per_world:.0f} cost-model flops/world-step, over the "
        f"recorded budget {FLOPS_PER_WORLD_STEP_BUDGET}. If the increase "
        "is intentional, re-measure and update the budget in this file "
        "and docs/perf.md in the same PR.")


def test_donated_run_peak_memory():
    """The donated run path aliases the whole input state (no double
    buffer): peak ≈ state + loop temporaries must stay under 1.2x the
    argument size (it was ~2.7x before donation + the single-pass
    insert's temp work)."""
    eng = _bug_config_engine()
    state = eng.init(np.arange(1024))
    comp = _compile_fresh(eng._run.lower(state, 4_000))
    ma = comp.memory_analysis()
    assert ma.alias_size_in_bytes == ma.argument_size_in_bytes, (
        "donation did not alias the full input state")
    peak = (ma.argument_size_in_bytes + ma.output_size_in_bytes
            + ma.temp_size_in_bytes - ma.alias_size_in_bytes)
    ratio = peak / ma.argument_size_in_bytes
    assert ratio <= PEAK_OVER_STATE_BUDGET, (
        f"donated-run peak is {ratio:.3f}x the argument state "
        f"(temp {ma.temp_size_in_bytes} B); the no-double-buffer "
        f"contract (analysis/budgets.json engine.run) allows at most "
        f"{PEAK_OVER_STATE_BUDGET}x")


def test_run_donates_its_input_state():
    """The documented contract: the state passed to run()/run_steps() is
    dead afterwards — reading it raises. (This is what the sweep, bench
    and every in-repo caller rely on; anyone holding the argument must
    rebind instead.)"""
    eng = _bug_config_engine()
    state = eng.init(np.arange(8))
    out = eng.run(state, max_steps=50)
    jax.block_until_ready(out)
    with pytest.raises(RuntimeError, match="deleted|donated"):
        _ = np.asarray(state.now)
