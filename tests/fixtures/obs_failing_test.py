"""A deliberately failing @madsim_tpu.test, used by tests/test_obs.py to
round-trip a repro bundle through `python -m madsim_tpu.obs replay`."""
import madsim_tpu as ms


@ms.test
async def always_fails():
    from madsim_tpu import time as simtime

    await simtime.sleep(0.01)
    raise RuntimeError("obs bundle fixture failure")
