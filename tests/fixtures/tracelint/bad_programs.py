"""Golden tracelint program fixtures.

Each function is a deliberately broken device program: the paired test
(tests/test_tracelint.py) traces it and asserts the matching TRC rule
fires — proving the rule would catch the same construct if it ever crept
into a real hot-path program. None of these run; they exist to be traced.
"""
import jax
import jax.numpy as jnp
import numpy as np


def leaky_callback(x):
    """TRC001 x2: a pure_callback and a debug.print (debug_callback)."""
    y = jax.pure_callback(lambda v: np.asarray(v) + 1,
                          jax.ShapeDtypeStruct((), jnp.int32), x)
    jax.debug.print("x={x}", x=x)
    return y


def callback_in_scan(x):
    """TRC001 nested under a scan body — the walker must recurse."""
    def body(carry, _):
        jax.debug.print("c={c}", c=carry)
        return carry + 1, None
    out, _ = jax.lax.scan(body, x, None, length=3)
    return out


def unstable_sort(x):
    """TRC002: equal keys land in backend-chosen order."""
    return jax.lax.sort(x, is_stable=False)


def float_scatter_accum(x, idx, upd):
    """TRC002: float accumulation onto possibly-duplicate indices — the
    reduction order (and so the rounding) is backend-chosen."""
    return x.at[idx].add(upd)


def int_scatter_accum(x, idx, upd):
    """Clean twin of the above: integer adds are exact regardless of
    order, so no finding."""
    return x.at[idx].add(upd)


def x64_leaky_sum(mask):
    """TRC003 (output drift): an unpinned jnp.sum widens i32 -> i64 when
    jax_enable_x64 is set — the exact leak class tracelint's first
    self-scan found (and fixed) in the engine's occupancy reduction."""
    return jnp.sum(mask.astype(jnp.int32))


def f64_intermediate(x):
    """TRC003 (widened intermediate): the f64 cast silently truncates to
    f32 without the x64 flag, so the two settings round differently even
    though the output dtype is pinned."""
    return (x.astype(jnp.float64) * 2).astype(jnp.float32)


def clean_program(x):
    """No findings: dtype-pinned, stable, callback-free."""
    order = jnp.argsort(x, stable=True)
    return jnp.sum(jnp.take(x, order), dtype=jnp.int32)
