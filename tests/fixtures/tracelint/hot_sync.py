# tracelint: hot-loop
"""Golden DET008/DET009 fixture: an orchestration loop that violates the
counted-fetch sync discipline in every way the rules cover. The first-
line marker opts the file into the hot-loop pass the real modules
(parallel/sweep.py, fleet/worker.py, obs/observatory.py) get by path."""
import jax
import jax.numpy as jnp
import numpy as np

_fetch = jax.device_get  # detlint: allow[DET008] reason=the fixture's sanctioned hook


def loop(runner, state):
    state, n_active = runner(state, jnp.int32(4))
    n = int(n_active)                # DET009: un-fetched conversion
    h = np.asarray(jnp.sum(state))   # DET008: inline materialization
    v = state.item()                 # DET008: forced sync method
    jax.block_until_ready(state)     # DET008: explicit barrier
    n_h = _fetch(n_active)
    ok = int(n_h)                    # clean: fetched first
    return n, h, v, ok
