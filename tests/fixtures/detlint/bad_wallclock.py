"""DET001 golden fixture: wall-clock reads escaping virtual time.

Never imported by tests — detlint parses it, so the aliased import must
not hide the escape.
"""
import time as _walltime
from datetime import datetime


def stamp():
    t0 = _walltime.time()
    _walltime.sleep(0.1)
    return t0, datetime.now()
