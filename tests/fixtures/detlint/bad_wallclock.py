"""DET001 golden fixture: wall-clock reads escaping virtual time.

Never imported by tests — detlint parses it, so the aliased import must
not hide the escape.
"""
import time as _walltime
from datetime import datetime


def stamp():
    t0 = _walltime.time()
    _walltime.sleep(0.1)
    return t0, datetime.now()


def cpu_clocks(loop):
    # Per-thread CPU clocks and the event loop's host monotonic clock —
    # all three read host time, none pass through the virtual clock.
    a = _walltime.thread_time()
    b = _walltime.thread_time_ns()
    return a, b, loop.time()
