"""Golden fixture: wall-clock *decode* calls in a timeline/export path.

Timeline timestamps must come from virtual time (obs/timeline.py): the
no-operand decode forms read the host clock and make two replays of one
seed render different bytes — DET001. The explicit-operand forms are
pure converters and stay clean.
"""
import time


def render_header(virtual_us: int):
    stamp = time.ctime()              # reads the wall clock
    local = time.localtime()          # reads the wall clock
    label = time.strftime("%H:%M")    # 1-arg form defaults to "now"
    ok = time.ctime(virtual_us / 1e6)                    # pure conversion
    ok2 = time.strftime("%H:%M", time.gmtime(virtual_us / 1e6))  # pure
    return stamp, local, label, ok, ok2
