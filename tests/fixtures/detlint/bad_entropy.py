"""DET002 golden fixture: ambient entropy bypassing the seeded RNG."""
import os
import random
import secrets
import uuid


def draw():
    return (os.urandom(8),
            random.random(),
            uuid.uuid4(),
            secrets.token_bytes(4),
            random.SystemRandom().randint(0, 7))
