"""Golden fixture: DET007 — jax.profiler capture (and its wall-clock
telemetry companions) started from engine/step code. Profiling belongs
to the observatory layer (obs/observatory.py ProfilerWindow /
sweep(profile_dir=...)), never inside simulation code where the capture
observes host time and scheduling."""
import jax
from time import perf_counter


def step(state):
    jax.profiler.start_trace("/tmp/steptrace")          # DET007
    t0 = perf_counter()                                 # DET001
    out = state + 1
    with jax.profiler.TraceAnnotation("hot-step"):      # DET007
        out = out * 2
    jax.profiler.stop_trace()                           # DET007
    return out, perf_counter() - t0                     # DET001
