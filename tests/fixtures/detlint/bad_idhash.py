"""DET006 golden fixture: identity-keyed ordering (allocation-dependent)."""


def order(nodes, tasks):
    ranked = sorted(nodes, key=id)
    tasks.sort(key=lambda t: hash(t))
    return ranked, tasks
