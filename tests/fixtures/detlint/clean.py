"""Clean golden fixture: the sim-safe twins of everything the bad
fixtures do — virtual time, seeded RNG, deterministic tasks, node-scoped
parallelism, the simulated network."""
from madsim_tpu import rand, task, time
from madsim_tpu.net import Endpoint, TcpStream


async def workload():
    rng = rand.thread_rng()
    await time.sleep(rng.gen_range_f64(0.0, 1.0))
    handle = task.spawn(ping())
    stamp = time.system_time()
    return await handle, stamp, task.available_parallelism()


async def ping():
    ep = await Endpoint.bind("10.0.0.1:0")
    stream = await TcpStream.connect("10.0.0.2:80")
    await stream.write_all(b"hello")
    ep.close()
    stream.close()
    return sorted(range(8), key=lambda n: n)
