"""DET004 golden fixture: host introspection used for sizing."""
import os


def pool_size():
    workers = min(32, (os.cpu_count() or 1) + 4)
    return workers, len(os.sched_getaffinity(0))
