"""DET005 golden fixture: raw sockets bypassing the simulated network."""
import socket


def dial(host, port):
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    s.connect((host, port))
    return socket.create_connection((host, port))
