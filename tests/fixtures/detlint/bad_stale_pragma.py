"""DET900 golden fixture: a pragma with nothing left to suppress."""


def quiet():
    return 1 + 1  # detlint: allow[DET001]
