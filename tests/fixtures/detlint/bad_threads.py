"""DET003 golden fixture: real concurrency inside a simulated world."""
import threading
from concurrent.futures import ThreadPoolExecutor


def fan_out(loop, work):
    t = threading.Thread(target=work)
    t.start()
    pool = ThreadPoolExecutor()
    return loop.run_in_executor(pool, work)
