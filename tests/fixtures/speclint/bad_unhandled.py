"""speclint golden fixture: SPC011 — a reachable kind with no handler.

``h_ping`` emits ``Drop``, so the kind is live protocol — but nothing
handles it and it is not declared in ``ignore=(...)``: every delivered
``Drop`` would be silently swallowed by the compiled dispatch.
"""
from madsim_tpu.actorc.spec import ActorSpec, Lane, Message, Word


def build() -> ActorSpec:
    lanes = (Lane("cnt", hi=100),)
    messages = (
        Message("Ping", (Word("x", 0, 100),)),
        Message("Drop", ()),
    )

    def h_ping(c):
        live = c.read("cnt") < 100
        c.write("cnt", c.clip(c.read("cnt") + 1, 0, 100), when=live)
        c.send("Drop", dst=c.src, when=live)

    def init(c):
        c.event("Ping", time=1_000, dst=0, words=[0])

    def invariant(v):
        return v.np.any(v.lane("cnt") < 0)

    return ActorSpec(
        name="lint_unhandled",
        n_nodes=2,
        lanes=lanes,
        messages=messages,
        handlers={"Ping": h_ping},
        init=init,
        invariant=invariant,
    )
