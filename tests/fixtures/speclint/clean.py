"""speclint golden fixture: a clean minimal protocol.

Two nodes ping-pong a bounded counter. Every declared kind is seeded or
emitted, every handler has effects, the single write stays inside the
i8 rail its declared range selects, and the echoed payload word stays
inside its declared range — zero findings, and the base the seeded-
defect fixtures in this directory are one edit away from.
"""
from madsim_tpu.actorc.spec import ActorSpec, Lane, Message, Word


def build() -> ActorSpec:
    lanes = (Lane("cnt", hi=100),)
    messages = (
        Message("Ping", (Word("x", 0, 100),)),
        Message("Pong", (Word("x", 0, 100),)),
    )

    def h_ping(c):
        live = c.read("cnt") < 100
        c.write("cnt", c.clip(c.read("cnt") + 1, 0, 100), when=live)
        c.send("Pong", dst=c.src, words=[c.arg("x")], when=live)

    def h_pong(c):
        live = c.read("cnt") < 100
        c.write("cnt", c.clip(c.read("cnt") + 1, 0, 100), when=live)

    def init(c):
        c.event("Ping", time=1_000, dst=0, words=[0])

    def invariant(v):
        return v.np.any(v.lane("cnt") < 0)

    return ActorSpec(
        name="lint_clean",
        n_nodes=2,
        lanes=lanes,
        messages=messages,
        handlers={"Ping": h_ping, "Pong": h_pong},
        init=init,
        invariant=invariant,
    )
