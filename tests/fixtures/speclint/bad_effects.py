"""speclint golden fixture: RNG/effect budgets (SPC040 + SPC041).

Two seeded defects, both known DSL gaps surfaced as diagnostics
instead of silent miscompiles:

- ``h_ping`` sends ``Pong`` twice with different payloads to different
  destinations and no disjointness proof — but the lowering has ONE
  merged message row per step, broadcasting ONE payload: the
  per-destination-payload pattern cannot lower (SPC040);
- ``h_pong`` draws from the RNG twice in one transition — the engine
  hands each event exactly one draw (the static-draw-shape rule), so
  the second ``u32()`` would alias the first (SPC041).
"""
from madsim_tpu.actorc.spec import ActorSpec, Lane, Message, Word


def build() -> ActorSpec:
    lanes = (Lane("cnt", hi=100),)
    messages = (
        Message("Ping", (Word("x", 0, 100),)),
        Message("Pong", (Word("x", 0, 100),)),
    )

    def h_ping(c):
        c.write("cnt", c.clip(c.read("cnt") + 1, 0, 100))
        c.send("Pong", dst=0, words=[c.arg("x")])
        c.send("Pong", dst=1, words=[0])  # second payload, same row

    def h_pong(c):
        a = c.u32() % 2
        b = c.u32() % 2  # the seeded defect: a second draw per event
        c.write("cnt", c.where(a == b, 1, 0))

    def init(c):
        c.event("Ping", time=1_000, dst=0, words=[0])

    def invariant(v):
        return v.np.any(v.lane("cnt") < 0)

    return ActorSpec(
        name="lint_effects",
        n_nodes=2,
        lanes=lanes,
        messages=messages,
        handlers={"Ping": h_ping, "Pong": h_pong},
        init=init,
        invariant=invariant,
    )
