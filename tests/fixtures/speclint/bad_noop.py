"""speclint golden fixture: SPC012 — a handler with no effects at all.

``h_pong`` neither writes, sends, arms, draws nor flags a bug, and
``Pong`` is not declared terminal: the transition compiles to a no-op
``where`` chain — dead weight that usually means a forgotten body.
"""
from madsim_tpu.actorc.spec import ActorSpec, Lane, Message, Word


def build() -> ActorSpec:
    lanes = (Lane("cnt", hi=100),)
    messages = (
        Message("Ping", (Word("x", 0, 100),)),
        Message("Pong", (Word("x", 0, 100),)),
    )

    def h_ping(c):
        live = c.read("cnt") < 100
        c.write("cnt", c.clip(c.read("cnt") + 1, 0, 100), when=live)
        c.send("Pong", dst=c.src, words=[c.arg("x")], when=live)

    def h_pong(c):
        pass  # the seeded defect: no effects, and Pong is not terminal

    def init(c):
        c.event("Ping", time=1_000, dst=0, words=[0])

    def invariant(v):
        return v.np.any(v.lane("cnt") < 0)

    return ActorSpec(
        name="lint_noop",
        n_nodes=2,
        lanes=lanes,
        messages=messages,
        handlers={"Ping": h_ping, "Pong": h_pong},
        init=init,
        invariant=invariant,
    )
