"""speclint golden fixture: timer discipline (SPC020 + SPC021).

Two seeded defects:

- ``h_ping`` arms ``Tick`` twice under conditions (``cnt > 0`` and
  ``cnt > 1``) with no static disjointness proof — the single merged
  timer row is last-write-wins, so the first arm silently vanishes
  whenever both fire (SPC021, a known DSL gap surfaced instead of
  miscompiled);
- the ``Dead`` timer has a handler but no transition, restart hook or
  init event ever arms it (SPC020) — which also makes the kind
  unreachable (SPC010): the firing path is dead by construction.
"""
from madsim_tpu.actorc.spec import ActorSpec, Lane, Message, Word


def build() -> ActorSpec:
    lanes = (Lane("cnt", hi=100),)
    messages = (
        Message("Ping", (Word("x", 0, 100),)),
        Message("Tick", (), timer=True),
        Message("Dead", (), timer=True),
    )

    def h_ping(c):
        some = c.read("cnt") > 0
        more = c.read("cnt") > 1  # overlaps `some`: not disjoint
        c.arm("Tick", delay=1_000, when=some)
        c.arm("Tick", delay=2_000, when=more)

    def h_tick(c):
        c.write("cnt", c.clip(c.read("cnt") + 1, 0, 100))

    def h_dead(c):
        c.write("cnt", 0, when=c.read("cnt") > 0)

    def init(c):
        c.event("Ping", time=1_000, dst=0, words=[0])

    def invariant(v):
        return v.np.any(v.lane("cnt") < 0)

    return ActorSpec(
        name="lint_timer",
        n_nodes=2,
        lanes=lanes,
        messages=messages,
        handlers={"Ping": h_ping, "Tick": h_tick, "Dead": h_dead},
        init=init,
        invariant=invariant,
    )
