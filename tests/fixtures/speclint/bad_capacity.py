"""speclint golden fixture: capacity proofs (SPC030 + SPC031).

Two seeded defects of the overflow class TRC005 cannot see, because
the saturating ``narrow`` on the write path is placed *by design*:

- ``small`` declares [0, 100] and packs to int8, but ``h_ping`` writes
  ``small + 100`` — static bound [100, 200], past the 127 rail: the
  value would saturate silently at rest (SPC030);
- ``h_ping`` sends ``Pong`` with word ``x + 50`` — static bound
  [50, 150], outside the word's declared [0, 100] that the receiving
  ``arg()`` read assumes (SPC031).
"""
from madsim_tpu.actorc.spec import ActorSpec, Lane, Message, Word


def build() -> ActorSpec:
    lanes = (Lane("small", hi=100),)
    messages = (
        Message("Ping", (Word("x", 0, 100),)),
        Message("Pong", (Word("x", 0, 100),)),
    )

    def h_ping(c):
        live = c.read("small") < 100
        c.write("small", c.read("small") + 100, when=live)
        c.send("Pong", dst=c.src, words=[c.arg("x") + 50], when=live)

    def h_pong(c):
        live = c.read("small") < 100
        c.write("small", c.clip(c.read("small") + 1, 0, 100), when=live)

    def init(c):
        c.event("Ping", time=1_000, dst=0, words=[0])

    def invariant(v):
        return v.np.any(v.lane("small") < 0)

    return ActorSpec(
        name="lint_capacity",
        n_nodes=2,
        lanes=lanes,
        messages=messages,
        handlers={"Ping": h_ping, "Pong": h_pong},
        init=init,
        invariant=invariant,
    )
