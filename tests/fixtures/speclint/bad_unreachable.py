"""speclint golden fixture: SPC010 — a kind nobody seeds or emits.

The ``Lost`` message has a perfectly good handler, but no init event
seeds it and no reachable transition sends it: dead protocol that a
fault schedule can never exercise.
"""
from madsim_tpu.actorc.spec import ActorSpec, Lane, Message, Word


def build() -> ActorSpec:
    lanes = (Lane("cnt", hi=100),)
    messages = (
        Message("Ping", (Word("x", 0, 100),)),
        Message("Pong", (Word("x", 0, 100),)),
        Message("Lost", ()),
    )

    def h_ping(c):
        live = c.read("cnt") < 100
        c.write("cnt", c.clip(c.read("cnt") + 1, 0, 100), when=live)
        c.send("Pong", dst=c.src, words=[c.arg("x")], when=live)

    def h_pong(c):
        live = c.read("cnt") < 100
        c.write("cnt", c.clip(c.read("cnt") + 1, 0, 100), when=live)

    def h_lost(c):
        # A real transition — effects and all — that can never run.
        c.write("cnt", 0, when=c.read("cnt") > 0)

    def init(c):
        c.event("Ping", time=1_000, dst=0, words=[0])

    def invariant(v):
        return v.np.any(v.lane("cnt") < 0)

    return ActorSpec(
        name="lint_unreachable",
        n_nodes=2,
        lanes=lanes,
        messages=messages,
        handlers={"Ping": h_ping, "Pong": h_pong, "Lost": h_lost},
        init=init,
        invariant=invariant,
    )
