"""speclint golden fixture: DET900 — a stale SPC pragma.

The spec itself is clean; the ``allow[SPC030]`` pragma below suppresses
nothing, and pass 4 owns SPC codes, so IT flags the stale pragma as
DET900 (pass 1 scanning this same file must stay silent about it — it
does not own the SPC prefix).
"""
from madsim_tpu.actorc.spec import ActorSpec, Lane, Message, Word


def build() -> ActorSpec:
    lanes = (Lane("cnt", hi=100),)
    messages = (
        Message("Ping", (Word("x", 0, 100),)),
        Message("Pong", (Word("x", 0, 100),)),
    )

    def h_ping(c):
        live = c.read("cnt") < 100
        # The write below stays inside the i8 rail — the pragma is stale.
        c.write("cnt", c.clip(c.read("cnt") + 1, 0, 100),
                when=live)  # detlint: allow[SPC030]
        c.send("Pong", dst=c.src, words=[c.arg("x")], when=live)

    def h_pong(c):
        live = c.read("cnt") < 100
        c.write("cnt", c.clip(c.read("cnt") + 1, 0, 100), when=live)

    def init(c):
        c.event("Ping", time=1_000, dst=0, words=[0])

    def invariant(v):
        return v.np.any(v.lane("cnt") < 0)

    return ActorSpec(
        name="lint_stale_pragma",
        n_nodes=2,
        lanes=lanes,
        messages=messages,
        handlers={"Ping": h_ping, "Pong": h_pong},
        init=init,
        invariant=invariant,
    )
