"""speclint golden fixture: durability flow (SPC050).

``mem`` is declared volatile (``durable=False``) and ``h_ping`` reads
it, but the spec has no ``on_restart`` hook: after a node restart the
read sees the reset value with nothing to reconstruct it — the classic
stable-storage violation, statically visible from the declarations.
"""
from madsim_tpu.actorc.spec import ActorSpec, Lane, Message, Word


def build() -> ActorSpec:
    lanes = (Lane("mem", hi=100, durable=False),)
    messages = (
        Message("Ping", (Word("x", 0, 100),)),
        Message("Pong", (Word("x", 0, 100),)),
    )

    def h_ping(c):
        live = c.read("mem") < 100
        c.write("mem", c.clip(c.read("mem") + 1, 0, 100), when=live)
        c.send("Pong", dst=c.src, words=[c.arg("x")], when=live)

    def h_pong(c):
        c.write("mem", 1)  # write-only: not a durability read

    def init(c):
        c.event("Ping", time=1_000, dst=0, words=[0])

    def invariant(v):
        return v.np.any(v.lane("mem") < 0)

    return ActorSpec(
        name="lint_durability",
        n_nodes=2,
        lanes=lanes,
        messages=messages,
        handlers={"Ping": h_ping, "Pong": h_pong},
        init=init,
        invariant=invariant,
    )
