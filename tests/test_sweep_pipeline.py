"""Pipelined sweep orchestration (docs/perf.md "Pipelined orchestration").

The contract under test: the dispatch-ahead, superstepped loop
(``sweep(pipeline=True)``, the default) returns results — per-seed
observations, failing-seed attribution, per-chunk occupancy history —
bitwise identical to the serial per-chunk reference loop
(``pipeline=False``), for every actor family and every loop mode
(plain / recycled / compacted / stop_on_first_bug / max_steps /
checkpointed), while crossing the host boundary only with the intended
occupancy/bug scalars per superstep and cutting host dispatches by the
superstep fan-in.
"""
import importlib

import numpy as np
import pytest

# The package re-exports the sweep FUNCTION as an attribute named like
# the submodule; resolve the module itself for the _fetch hook.
sweep_mod = importlib.import_module("madsim_tpu.parallel.sweep")
from madsim_tpu.engine import (
    DeviceEngine,
    EngineConfig,
    PBActor,
    PBDeviceConfig,
    RaftActor,
    RaftDeviceConfig,
    TPCActor,
    TPCDeviceConfig,
)
from madsim_tpu.parallel.sweep import sweep


@pytest.fixture(scope="module")
def raft_eng():
    # The flagship family with an injected bug: occupancy actually drops
    # across chunks (stop_on_bug freezes buggy worlds), exercising the
    # recycle/compact thresholds.
    rcfg = RaftDeviceConfig(n=3, buggy_double_vote=True)
    cfg = EngineConfig(n_nodes=3, outbox_cap=4, queue_cap=64,
                      t_limit_us=1_500_000, stop_on_bug=True)
    return DeviceEngine(RaftActor(rcfg), cfg)


@pytest.fixture(scope="module")
def pb_eng():
    return DeviceEngine(
        PBActor(PBDeviceConfig(n=3, n_writes=4)),
        EngineConfig(n_nodes=3, outbox_cap=4, queue_cap=64,
                     t_limit_us=1_500_000, loss_rate=0.05))


@pytest.fixture(scope="module")
def tpc_eng():
    return DeviceEngine(
        TPCActor(TPCDeviceConfig(n=4, n_txns=4, buggy_presumed_commit=True)),
        EngineConfig(n_nodes=4, outbox_cap=5, queue_cap=64,
                     t_limit_us=1_500_000, loss_rate=0.1))


def both_loops(eng, seeds, **kw):
    ser = sweep(None, eng.cfg, seeds, engine=eng, pipeline=False, **kw)
    pip = sweep(None, eng.cfg, seeds, engine=eng, pipeline=True, **kw)
    return ser, pip


def assert_bitwise_equal(ser, pip):
    assert ser.steps_run == pip.steps_run
    np.testing.assert_array_equal(ser.n_active_history, pip.n_active_history)
    np.testing.assert_array_equal(ser.n_active_chunks, pip.n_active_chunks)
    for k in ser.observations:
        np.testing.assert_array_equal(ser.observations[k],
                                      pip.observations[k], err_msg=k)
    assert ser.failing_seeds == pip.failing_seeds
    # Same executed chunks, same utilization accounting.
    assert ser.loop_stats["chunks"] == pip.loop_stats["chunks"]
    assert ser.world_utilization == pip.world_utilization


def test_pipelined_matches_serial_raft_all_modes(raft_eng):
    """Every loop mode of the flagship family: the dispatch-ahead
    superstep loop is bitwise the serial loop, including the early exits
    (stop_on_first_bug / max_steps) where the in-flight superstep must be
    a pass-through no-op."""
    seeds = np.arange(200)  # not a mesh multiple: stream tail exercised
    for kw in (dict(chunk_steps=64, max_steps=10_000),
               dict(chunk_steps=64, max_steps=10_000,
                    recycle=True, batch_worlds=48),
               dict(chunk_steps=64, max_steps=10_000, compact=True),
               dict(chunk_steps=64, max_steps=10_000,
                    stop_on_first_bug=True),
               dict(chunk_steps=64, max_steps=128),
               dict(chunk_steps=64, max_steps=10_000,
                    stop_on_first_bug=True, recycle=True, batch_worlds=16)):
        ser, pip = both_loops(raft_eng, seeds, **kw)
        assert_bitwise_equal(ser, pip)
    assert pip.loop_stats["pipelined"] and not ser.loop_stats["pipelined"]


def test_pipelined_matches_serial_pb(pb_eng):
    seeds = np.arange(96)
    ser, pip = both_loops(pb_eng, seeds, chunk_steps=64, max_steps=10_000)
    assert_bitwise_equal(ser, pip)
    ser, pip = both_loops(pb_eng, seeds, chunk_steps=64, max_steps=10_000,
                          recycle=True, batch_worlds=32)
    assert_bitwise_equal(ser, pip)


def test_pipelined_matches_serial_tpc(tpc_eng):
    seeds = np.arange(96)
    ser, pip = both_loops(tpc_eng, seeds, chunk_steps=64, max_steps=10_000)
    assert_bitwise_equal(ser, pip)
    ser, pip = both_loops(tpc_eng, seeds, chunk_steps=64, max_steps=10_000,
                          recycle=True, batch_worlds=32)
    assert_bitwise_equal(ser, pip)


def test_pipelined_checkpoint_interplay(raft_eng, tmp_path):
    """Checkpointing + pipelining: donation stays disabled while the
    async writer may read a submitted state (a donated buffer would be
    invalidated mid-read — this test crashing or corrupting would catch
    it), the snapshot cadence still lands durable states, and a resumed
    pipelined sweep continues bit-exactly."""
    seeds = np.arange(40)
    kw = dict(chunk_steps=128, max_steps=4_000)
    full_ser = sweep(None, raft_eng.cfg, seeds, engine=raft_eng,
                     pipeline=False, **kw)
    path = str(tmp_path / "pipe.npz")
    full_pip = sweep(None, raft_eng.cfg, seeds, engine=raft_eng,
                     pipeline=True, checkpoint_path=path,
                     checkpoint_every_chunks=1, **kw)
    for k in full_ser.observations:
        np.testing.assert_array_equal(full_ser.observations[k],
                                      full_pip.observations[k], err_msg=k)
    # Interrupted pipelined sweep (2 chunks), then a pipelined resume:
    # the merged trajectory equals the unbroken run's, bit for bit.
    path2 = str(tmp_path / "resume.npz")
    sweep(None, raft_eng.cfg, seeds, engine=raft_eng, chunk_steps=128,
          max_steps=256, checkpoint_path=path2, checkpoint_every_chunks=1)
    resumed = sweep(None, raft_eng.cfg, seeds, engine=raft_eng,
                    chunk_steps=128, max_steps=4_000, checkpoint_path=path2,
                    resume=True)
    for k in full_ser.observations:
        np.testing.assert_array_equal(full_ser.observations[k],
                                      resumed.observations[k], err_msg=k)


def test_n_active_chunk_index_contract(raft_eng):
    """``n_active_chunks`` records the executed-chunk index each history
    entry was measured at: entrywise aligned, strictly increasing, and
    identical between the serial, pipelined, AND fused loops (the
    measurement sequence is per-chunk in all three — pipelining only
    delays when the host READS it, and the fused loop records the chunk
    index inside the device program, so a mega-dispatch of K chunks
    lands K correctly-indexed entries, not one skewed batch)."""
    seeds = np.arange(200)
    kw = dict(chunk_steps=64, max_steps=10_000, recycle=True,
              batch_worlds=48)
    ser, pip = both_loops(raft_eng, seeds, **kw)
    fus = sweep(None, raft_eng.cfg, seeds, engine=raft_eng, fused=True,
                **kw)
    for res in (ser, pip, fus):
        assert res.n_active_chunks.shape == res.n_active_history.shape
        assert (np.diff(res.n_active_chunks) > 0).all()
        assert res.n_active_chunks[0] == 0
        assert res.n_active_chunks[-1] == res.loop_stats["chunks"] - 1
    np.testing.assert_array_equal(ser.n_active_chunks, pip.n_active_chunks)
    np.testing.assert_array_equal(ser.n_active_chunks, fus.n_active_chunks)
    np.testing.assert_array_equal(ser.n_active_history,
                                  fus.n_active_history)


def test_sync_discipline_counted_fetches(raft_eng, monkeypatch):
    """Tier-1 sync discipline: in the steady-state superstep loop, the
    ONLY device→host pulls are the per-superstep occupancy/bug scalar
    batches (a few hundred bytes), plus one bucketed frozen-tail slice
    per retirement event and the single final merge — never a full
    per-world observation pull mid-loop. Counted via the sweep module's
    ``_fetch`` hook, through which every loop-side pull is routed."""
    calls = []
    real_fetch = sweep_mod._fetch

    def counting_fetch(tree):
        out = real_fetch(tree)
        import jax
        nbytes = sum(np.asarray(x).nbytes for x in jax.tree.leaves(out))
        calls.append(nbytes)
        return out

    monkeypatch.setattr(sweep_mod, "_fetch", counting_fetch)
    seeds = np.arange(96)

    # Plain sweep: no retirement events at all. Pulls = one scalar batch
    # per superstep dispatch + the final slot-index fetch for the merge.
    res = sweep(None, raft_eng.cfg, seeds, engine=raft_eng, chunk_steps=64,
                max_steps=10_000)
    st = res.loop_stats
    # One scalar batch per superstep READ; the one dispatched-ahead
    # superstep still in flight at the stop is never read at all.
    assert st["scalar_fetches"] <= st["dispatches"] \
        <= st["scalar_fetches"] + 1
    assert st["retire_fetches"] == 0
    assert len(calls) == st["scalar_fetches"] + 1  # + final idx fetch
    # Each steady-state pull is scalars + the K-wide history lane — a few
    # hundred bytes, never a per-world array of the 96-world batch.
    scalar_bytes = calls[:-1]
    assert max(scalar_bytes) <= 256, scalar_bytes

    # Recycled sweep: each refill/shrink adds exactly one (bucketed)
    # frozen-tail retirement pull; the steady-state pulls stay scalar.
    calls.clear()
    res = sweep(None, raft_eng.cfg, seeds, engine=raft_eng, chunk_steps=64,
                max_steps=10_000, recycle=True, batch_worlds=32)
    st = res.loop_stats
    assert st["retire_fetches"] >= 1
    assert st["scalar_fetches"] <= st["dispatches"] \
        <= st["scalar_fetches"] + 1
    assert len(calls) == st["scalar_fetches"] + st["retire_fetches"] + 1


def test_superstep_dispatch_reduction():
    """The tentpole's dispatch economics: on a long trajectory the
    adaptive superstep folds >= 4 chunks into one host dispatch (slow
    start doubles K up to superstep_max while supersteps run to plan)."""
    clean = DeviceEngine(
        RaftActor(RaftDeviceConfig(n=3, n_proposals=1)),
        EngineConfig(n_nodes=3, outbox_cap=4, t_limit_us=3_000_000))
    seeds = np.arange(48)
    # Fine chunks (8 steps) — exactly the granularity supersteps make
    # affordable, since the host no longer syncs per chunk.
    ser = sweep(None, clean.cfg, seeds, engine=clean, chunk_steps=8,
                max_steps=100_000, pipeline=False)
    pip = sweep(None, clean.cfg, seeds, engine=clean, chunk_steps=8,
                max_steps=100_000, pipeline=True)
    assert_bitwise_equal(ser, pip)
    # Serial pays one dispatch per chunk; the superstep loop must fold
    # the same chunks into <= 1/4 the dispatches.
    assert ser.loop_stats["dispatches"] == ser.loop_stats["chunks"]
    assert pip.loop_stats["chunks"] >= 32  # the workload really is long
    assert pip.loop_stats["dispatches"] * 4 <= pip.loop_stats["chunks"], \
        pip.loop_stats
    assert pip.loop_stats["chunks_per_dispatch"] >= 4
    # Dispatch-ahead really ran (one superstep in flight past the read).
    assert pip.loop_stats["dispatch_depth"] == 1


def test_binding_max_steps_respects_chunk_budget():
    """Review regression: the dispatch-ahead budget must reserve the
    planned chunks of the superstep already in the device queue but not
    yet read. With non-retiring worlds (the clean raft family stays at
    full occupancy for its first 6 chunks of 64 steps) and a binding
    ``max_steps`` in the c_max 5-8 window — where the adaptive K ramp
    (1, 1, 2, 4, ...) would otherwise overshoot — the pipelined loop
    must execute EXACTLY the serial loop's chunk budget, bitwise."""
    clean = DeviceEngine(
        RaftActor(RaftDeviceConfig(n=3, n_proposals=1)),
        EngineConfig(n_nodes=3, outbox_cap=4, t_limit_us=3_000_000))
    seeds = np.arange(24)
    for c_max in (5, 6, 7, 8):
        ser, pip = both_loops(clean, seeds, chunk_steps=64,
                              max_steps=64 * c_max)
        assert_bitwise_equal(ser, pip)
        assert pip.loop_stats["chunks"] <= c_max
        assert pip.steps_run <= 64 * c_max
    # In the fully non-retiring window the budget truly binds: the loop
    # runs the whole budget, never a chunk more.
    ser, pip = both_loops(clean, seeds, chunk_steps=64, max_steps=64 * 5)
    assert (pip.n_active_history == 24).all()  # nobody retired
    assert pip.loop_stats["chunks"] == 5 and pip.steps_run == 320


def test_zero_step_budget_runs_no_chunks():
    """Review regression: ``max_steps <= 0`` means a zero-chunk budget.
    The serial loop never enters its body; the pipelined loop must not
    force a min_one first chunk either."""
    clean = DeviceEngine(
        RaftActor(RaftDeviceConfig(n=3, n_proposals=1)),
        EngineConfig(n_nodes=3, outbox_cap=4, t_limit_us=3_000_000))
    seeds = np.arange(8)
    ser, pip = both_loops(clean, seeds, chunk_steps=64, max_steps=0)
    assert_bitwise_equal(ser, pip)
    assert ser.steps_run == pip.steps_run == 0
    assert ser.loop_stats["chunks"] == pip.loop_stats["chunks"] == 0
    assert pip.loop_stats["dispatches"] == 0
    assert pip.n_active_history.size == 0


@pytest.mark.parametrize("pipeline", [True, False])
def test_loop_stats_schema_both_paths(raft_eng, pipeline):
    """The documented ``loop_stats`` schema (docs/perf.md "Telemetry",
    docs/observability.md) holds on BOTH orchestration paths, with sane
    types and values — not just key presence on the default path."""
    res = sweep(None, raft_eng.cfg, np.arange(48), engine=raft_eng,
                chunk_steps=64, max_steps=2_048, pipeline=pipeline)
    ls = res.loop_stats
    documented = {"device_wait_s", "host_decision_s", "scalar_fetches",
                  "retire_fetches", "dispatch_depth", "dispatches_per_seed",
                  "seeds_per_dispatch", "epochs_on_device", "fused",
                  "pipelined", "superstep_max", "chunk_steps", "chunks",
                  "dispatches", "chunks_per_dispatch", "dispatch_s",
                  "retire_wait_s", "loop_wall_s"}
    assert documented <= set(ls), sorted(ls)
    assert ls["pipelined"] is pipeline
    assert ls["fused"] is False
    assert ls["epochs_on_device"] == 0   # host loops never refill on device
    assert ls["seeds_per_dispatch"] == pytest.approx(
        48 / ls["dispatches"], abs=1e-3)
    for key in ("device_wait_s", "host_decision_s", "dispatch_s",
                "retire_wait_s", "loop_wall_s"):
        assert isinstance(ls[key], float) and ls[key] >= 0.0, key
    for key in ("scalar_fetches", "retire_fetches", "dispatch_depth",
                "chunks", "dispatches", "superstep_max", "chunk_steps"):
        assert isinstance(ls[key], int) and ls[key] >= 0, key
    assert ls["chunks"] >= 1 and ls["dispatches"] >= 1
    assert ls["scalar_fetches"] >= 1
    assert ls["retire_fetches"] == 0       # plain sweep: nothing retires
    assert ls["chunk_steps"] == 64
    assert ls["superstep_max"] == (16 if pipeline else 1)
    assert ls["dispatches_per_seed"] == pytest.approx(
        ls["dispatches"] / 48, abs=1e-6)
    # Dispatch-ahead runs exactly one superstep deep; the serial loop
    # never dispatches ahead at all.
    assert ls["dispatch_depth"] == (1 if pipeline else 0)
    assert ls["loop_wall_s"] >= ls["host_decision_s"]


def test_superstep_telemetry_fields(raft_eng):
    """SweepResult.loop_stats carries the bench contract fields
    (bench_results.json configs.*.sweep_loop, asserted by make smoke)."""
    res = sweep(None, raft_eng.cfg, np.arange(48), engine=raft_eng,
                chunk_steps=64, max_steps=512)
    need = {"pipelined", "fused", "chunks", "dispatches",
            "chunks_per_dispatch", "dispatches_per_seed",
            "seeds_per_dispatch", "epochs_on_device", "dispatch_depth",
            "device_wait_s", "host_decision_s", "dispatch_s",
            "retire_wait_s", "scalar_fetches", "retire_fetches",
            "loop_wall_s", "superstep_max", "chunk_steps"}
    assert need <= set(res.loop_stats), res.loop_stats
    assert res.loop_stats["device_wait_s"] >= 0.0
    assert res.loop_stats["dispatches_per_seed"] == pytest.approx(
        res.loop_stats["dispatches"] / 48, abs=1e-6)
