"""Packed lane dtypes (engine/lanes.py Lanes registry, PR "Roofline
round 2").

Contracts under test:

- **bitwise crosscheck**: `EngineConfig(packed=True)` (the default)
  walks bit-identical trajectories to the int32 reference profile
  (`packed=False`) — the sweep-level matrix rides tests/test_obs.py;
  here the targeted engine-level cases live (generation-lane wrap,
  net-param split encoding).
- **state bytes**: the packed profile is <= 0.6x the wide profile on
  the canonical ledger config, and the checked-in ledger's
  `state_bytes_per_world` equals what the state pytree actually weighs.
- **dtype-boundary guards**: capacity knobs that would overflow a
  narrow lane are rejected with pointed ValueErrors at EngineConfig
  construction; saturating/wrapping narrows behave as documented.
- **TRC005**: the tracelint narrow-dtype discipline rule flags
  unannotated i8/i16 -> i32 widenings and sanctions lanes.widen.
- **tools/update_budgets.py** refuses to clobber a dirty ledger.
"""
import dataclasses
import json
import os
import subprocess

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from madsim_tpu.engine import (
    DeviceEngine,
    EngineConfig,
    FAULT_KILL,
    FAULT_RESTART,
    RaftActor,
    RaftDeviceConfig,
)
from madsim_tpu.engine.lanes import (
    PACKED,
    WIDE,
    join_wide,
    narrow,
    narrow_wrap,
    split_wide,
    widen,
)


def _state_bytes_per_world(state, w):
    return sum(leaf.nbytes for leaf in jax.tree.leaves(state)) / w


# ---------------------------------------------------------------------------
# EngineConfig dtype-boundary guards
# ---------------------------------------------------------------------------

def test_packed_rejects_node_count_over_127():
    with pytest.raises(ValueError, match="int8.*127.*packed=False"):
        EngineConfig(n_nodes=128)
    # The escape hatch takes the same cluster width.
    assert EngineConfig(n_nodes=128, packed=False).n_nodes == 128
    # And the engine-level 256 ceiling still backs the wide profile.
    assert EngineConfig(n_nodes=127).packed


def test_packed_rejects_queue_cap_over_i16():
    with pytest.raises(ValueError, match="int16.*32767.*packed=False"):
        EngineConfig(n_nodes=3, queue_cap=32_768)
    assert EngineConfig(n_nodes=3, queue_cap=32_767).queue_cap == 32_767
    assert EngineConfig(n_nodes=3, queue_cap=40_000,
                        packed=False).queue_cap == 40_000


def test_event_kind_range_guard_covers_i8_codes():
    # Event kinds (and fault/drop-cause codes, which share the code
    # lane) are capped at 64 by DeviceEngine — comfortably inside i8.
    class WideKinds:
        num_kinds = 65

    with pytest.raises(ValueError, match="num_kinds must be <= 64"):
        DeviceEngine(WideKinds(), EngineConfig(n_nodes=3))


def test_lane_registry_profiles():
    assert PACKED.node == jnp.int8 and PACKED.code == jnp.int8
    assert PACKED.slot == jnp.int16 and PACKED.payload == jnp.int16
    assert PACKED.time == jnp.int32 and PACKED.counter == jnp.int32
    assert all(d == jnp.int32 for d in
               (WIDE.node, WIDE.code, WIDE.slot, WIDE.payload))
    assert EngineConfig(n_nodes=3).lanes == PACKED
    assert EngineConfig(n_nodes=3, packed=False).lanes == WIDE


# ---------------------------------------------------------------------------
# Saturate / wrap / split helpers
# ---------------------------------------------------------------------------

def test_narrow_saturates_and_wide_is_identity():
    v = jnp.asarray([-40_000, -1, 0, 127, 128, 32_767, 32_768, 2**31 - 1],
                    jnp.int32)
    np.testing.assert_array_equal(
        np.asarray(narrow(v, jnp.int16)),
        [-32768, -1, 0, 127, 128, 32767, 32767, 32767])
    np.testing.assert_array_equal(
        np.asarray(narrow(v, jnp.int8)),
        [-128, -1, 0, 127, 127, 127, 127, 127])
    # Wide profile: identity (no clip, no cast — zero-cost reference path).
    same = narrow(v, jnp.int32)
    assert same.dtype == jnp.int32
    np.testing.assert_array_equal(np.asarray(same), np.asarray(v))
    assert narrow(v, jnp.int16).dtype == jnp.int16


def test_narrow_wrap_is_modular():
    v = jnp.asarray([0, 127, 128, 255, 256, 511], jnp.int32)
    w = np.asarray(narrow_wrap(v, jnp.int8))
    # The contract the generation lane relies on: widened & 0xFF == mod 256.
    np.testing.assert_array_equal(np.asarray(widen(w)) & 0xFF,
                                  np.asarray(v) % 256)


def test_split_join_roundtrip_covers_full_int32_range():
    vals = jnp.asarray([0, 1, 5_000, 32_767, 32_768, 65_535, 65_536,
                        1_000_000, 2_000_000_000, 2**31 - 1], jnp.int32)
    lo, hi = split_wide(vals)
    # Both halves must survive the saturating int16 narrow untouched —
    # that is what lets them ride the packed payload lane.
    np.testing.assert_array_equal(np.asarray(narrow(lo, jnp.int16)),
                                  np.asarray(lo))
    np.testing.assert_array_equal(np.asarray(narrow(hi, jnp.int16)),
                                  np.asarray(hi))
    np.testing.assert_array_equal(np.asarray(join_wide(lo, hi)),
                                  np.asarray(vals))


# ---------------------------------------------------------------------------
# Generation-lane wrap: i8 gen must agree with the i32 reference mod 256
# ---------------------------------------------------------------------------

def test_gen_lane_wraps_identically_to_wide_reference():
    """96 kill/restart pairs push node 0's generation past the int8 sign
    boundary at 127 while a pending-timer workload keeps exercising the
    stale-timer compare. Packed and wide must agree on every observation
    (generations compare mod 256 in both profiles). (queue_cap must hold
    the whole preloaded fault schedule — 192 rows — or kills get dropped
    and the generation counter never crosses the boundary.)"""
    rows = []
    for i in range(96):
        t = 10_000 + i * 4_000
        rows.append([t, FAULT_KILL, 0, 0])
        rows.append([t + 2_000, FAULT_RESTART, 0, 0])
    faults = np.asarray(rows, np.int32)
    cfg = EngineConfig(n_nodes=3, outbox_cap=4, queue_cap=256,
                       t_limit_us=450_000, stop_on_bug=False)
    mk = lambda: RaftActor(RaftDeviceConfig(n=3))  # noqa: E731
    ep = DeviceEngine(mk(), cfg)
    ew = DeviceEngine(mk(), dataclasses.replace(cfg, packed=False))
    sp = ep.run(ep.init(np.arange(8), faults=faults), 1_600)
    sw = ew.run(ew.init(np.arange(8), faults=faults), 1_600)
    assert sp.gen.dtype == jnp.int8 and sw.gen.dtype == jnp.int32
    # The wide gen really did pass the i8 sign boundary.
    assert int(np.asarray(sw.gen).max()) > 127
    np.testing.assert_array_equal(np.asarray(sp.gen, np.int32) & 0xFF,
                                  np.asarray(sw.gen) & 0xFF)
    op, ow = ep.observe(sp), ew.observe(sw)
    for k in ow:
        np.testing.assert_array_equal(op[k], ow[k], err_msg=k)


# ---------------------------------------------------------------------------
# State bytes: the 0.6x contract and the ledger's honesty
# ---------------------------------------------------------------------------

def test_packed_state_bytes_at_most_0_6x_wide():
    # The canonical ledger config (analysis/budgets.json engine.run).
    cfg = EngineConfig(n_nodes=3, outbox_cap=4, queue_cap=64,
                       t_limit_us=2_000_000, stop_on_bug=False)
    rcfg = RaftDeviceConfig(n=3, buggy_double_vote=True)
    w = 8
    packed = _state_bytes_per_world(
        DeviceEngine(RaftActor(rcfg), cfg).init(np.arange(w)), w)
    wide = _state_bytes_per_world(
        DeviceEngine(RaftActor(rcfg),
                     dataclasses.replace(cfg, packed=False))
        .init(np.arange(w)), w)
    assert packed <= 0.6 * wide, (
        f"packed state weighs {packed:.0f} B/world vs wide {wide:.0f} — "
        f"ratio {packed / wide:.4f} broke the 0.6x contract: a narrow "
        "lane regressed to a wide dtype")

    from madsim_tpu.analysis import budgets as B

    entry = B.load_ledger()["programs"]["engine.run"]
    ledger_val = entry["state_bytes_per_world"]["measured"]
    # XLA's argument accounting and the pytree's nbytes must agree —
    # if they drift, the ledger is measuring something else.
    assert ledger_val == pytest.approx(packed), (
        f"ledger state_bytes_per_world {ledger_val} != measured {packed}")
    assert B.budget_for(B.load_ledger(), "engine.run",
                        "state_bytes_per_world") is not None


# ---------------------------------------------------------------------------
# TRC005: narrow-dtype discipline
# ---------------------------------------------------------------------------

def test_trc005_flags_unannotated_widening_and_sanctions_lanes():
    from madsim_tpu.analysis.tracelint import check_narrow_discipline

    def leaky(x):
        return x + jnp.int32(1)  # implicit i16 -> i32 promotion

    findings = check_narrow_discipline(
        "scratch", jax.make_jaxpr(leaky)(jnp.zeros((4,), jnp.int16)).jaxpr)
    assert len(findings) == 1 and findings[0].rule == "TRC005"
    assert "int16 -> int32" in findings[0].message

    def disciplined(x):
        return widen(x) + jnp.int32(1)

    assert not check_narrow_discipline(
        "scratch",
        jax.make_jaxpr(disciplined)(jnp.zeros((4,), jnp.int16)).jaxpr)

    def narrowing(x):  # wide -> narrow is the write direction: not flagged
        return narrow(x, jnp.int16)

    assert not check_narrow_discipline(
        "scratch",
        jax.make_jaxpr(narrowing)(jnp.zeros((4,), jnp.int32)).jaxpr)


def test_trc005_applies_to_the_packed_programs():
    from madsim_tpu.analysis.tracelint import registry

    regs = registry()
    assert regs["engine.run"].packed
    assert regs["engine.pallas_step"].packed
    assert regs["engine.pallas_step"].budget  # own ledger entries


# ---------------------------------------------------------------------------
# tools/update_budgets.py: dirty-ledger refusal
# ---------------------------------------------------------------------------

def _git(cwd, *args):
    subprocess.run(["git", *args], cwd=cwd, check=True,
                   capture_output=True, text=True)


def test_update_budgets_refuses_dirty_ledger(tmp_path):
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tools"))
    import update_budgets

    repo = tmp_path / "repo"
    repo.mkdir()
    _git(repo, "init", "-q")
    _git(repo, "config", "user.email", "t@t")
    _git(repo, "config", "user.name", "t")
    ledger = repo / "budgets.json"
    ledger.write_text(json.dumps(
        {"schema": "madsim.tracelint.budgets/1", "justification": "seed",
         "programs": {}}))
    _git(repo, "add", "budgets.json")
    _git(repo, "commit", "-qm", "seed ledger")

    assert not update_budgets.ledger_dirty(str(ledger))
    original = ledger.read_text()
    ledger.write_text(original.replace("seed", "concurrent edit"))
    assert update_budgets.ledger_dirty(str(ledger))

    # The refusal happens before any measurement: instant, rc=2, and the
    # concurrent edit survives verbatim.
    rc = update_budgets.main(["--reason", "x", "--budgets", str(ledger)])
    assert rc == 2
    assert "concurrent edit" in ledger.read_text()

    # Untracked ledgers (no committed baseline) do not trip the guard.
    fresh = repo / "fresh.json"
    fresh.write_text(original)
    assert not update_budgets.ledger_dirty(str(fresh))

    # The repo's own ledger must be committed-clean for `make lint` to
    # regenerate without --force; this doubles as a reminder to commit
    # budgets.json in the same PR as any budget-moving change.
    from madsim_tpu.analysis import budgets as B

    here_dirty = update_budgets.ledger_dirty(B.DEFAULT_LEDGER)
    assert here_dirty in (True, False)  # callable against the real repo
