"""Actor-protocol conformance checker tests (engine/conformance.py)."""
import jax.numpy as jnp
import pytest

from madsim_tpu.engine import (
    ConformanceError, EngineConfig, Outbox,
    PBActor, PBDeviceConfig, RaftActor, RaftDeviceConfig,
    TPCActor, TPCDeviceConfig, check_actor,
)


def _family_names():
    from madsim_tpu.engine.families import actor_families

    return sorted(actor_families())


@pytest.mark.parametrize("name", _family_names())
def test_every_registered_family_conforms(name):
    """check_actor over EVERY registered family — hand-written and
    actorc-compiled alike — via the shared registry
    (engine/families.py), instead of the per-actor opt-in this test
    used to hard-code. Compiled actors must satisfy the same purity,
    determinism, restart and RNG draw-discipline bounds as the
    hand-written craft reference."""
    from madsim_tpu.engine.families import actor_families

    fam = actor_families()[name]
    actor, cfg = fam.conformance()
    report = check_actor(actor, cfg, n_worlds=32, max_steps=3_000,
                         require_divergence=fam.divergent)
    assert report["bug_rate"] == 0.0
    assert report["steps_mean"] > 1
    assert all(0 <= d <= 8 for d in report["draws_per_kind"])


def test_impure_handler_is_caught():
    import itertools

    counter = itertools.count()  # Python-level state: the impurity

    class Impure(RaftActor):
        def handle(self, cfg, s, ev, now, rng):
            s2, ob, rng2, bug = super().handle(cfg, s, ev, now, rng)
            # Sneak host-side mutable state into the trace: each CALL bakes
            # a different constant in, so two runs (fresh traces) differ.
            leak = jnp.int32(next(counter))
            return s2._replace(elections_won=s2.elections_won + 0 * leak
                               + leak), ob, rng2, bug

    actor = Impure(RaftDeviceConfig(n=3))
    cfg = EngineConfig(n_nodes=3, outbox_cap=4, queue_cap=64,
                       t_limit_us=2_000_000)
    with pytest.raises(ConformanceError, match="impure|diverged"):
        check_actor(actor, cfg, n_worlds=16, max_steps=1_000)


def test_float_state_is_rejected():
    class FloatState(RaftActor):
        def init(self, cfg, rng):
            s, evs, rng = super().init(cfg, rng)
            return s._replace(
                first_leader_time=jnp.float32(s.first_leader_time)), evs, rng

    actor = FloatState(RaftDeviceConfig(n=3))
    cfg = EngineConfig(n_nodes=3, outbox_cap=4, queue_cap=64,
                       t_limit_us=2_000_000)
    with pytest.raises(ConformanceError, match="dtype"):
        check_actor(actor, cfg, n_worlds=16, max_steps=500)


def test_handler_dtype_drift_is_caught():
    class Drift(RaftActor):
        def handle(self, cfg, s, ev, now, rng):
            s2, ob, rng2, bug = super().handle(cfg, s, ev, now, rng)
            # A handler that floats a leaf mid-run: the classic cryptic
            # while-loop carry mismatch, surfaced as ConformanceError.
            return s2._replace(
                elections_won=s2.elections_won * jnp.float32(1.0)), \
                ob, rng2, bug

    actor = Drift(RaftDeviceConfig(n=3))
    cfg = EngineConfig(n_nodes=3, outbox_cap=4, queue_cap=64,
                       t_limit_us=2_000_000)
    with pytest.raises(ConformanceError, match="carry mismatch|dtype"):
        check_actor(actor, cfg, n_worlds=16, max_steps=500)


def test_seed_insensitive_actor_is_caught():
    class Frozen:
        num_kinds = 1

        def init(self, cfg, rng):
            from madsim_tpu.engine.queue import Event

            s = {"x": jnp.zeros((cfg.n_nodes,), jnp.int32)}
            evs = [Event.make(time=10, kind=0,
                              payload_words=cfg.payload_words)]
            return s, evs, rng

        def handle(self, cfg, s, ev, now, rng):
            return s, Outbox.empty(cfg), rng, jnp.asarray(False)

        def on_restart(self, cfg, s, node, now, rng):
            return s, Outbox.empty(cfg), rng

        def invariant(self, cfg, s):
            return jnp.asarray(False)

        def observe(self, cfg, s):
            return {"x0": s["x"][..., 0]}

    cfg = EngineConfig(n_nodes=2, outbox_cap=3, queue_cap=8,
                       t_limit_us=1_000_000)
    with pytest.raises(ConformanceError, match="randomness"):
        check_actor(Frozen(), cfg, n_worlds=16, max_steps=100)
