"""Tests for the C++ native host core: build, parity, interchangeability."""
import subprocess
import sys

import numpy as np
import pytest

from madsim_tpu import native
from madsim_tpu.ops.threefry import (
    draw_np, seed_to_key, derive_stream_np, threefry2x32_scalar,
)


def test_native_builds_and_loads():
    lib = native.get_lib()
    if lib is None:
        pytest.skip("no native toolchain in this environment")
    assert native.available()


def test_scalar_threefry_matches_numpy():
    rng = np.random.default_rng(0)
    for _ in range(64):
        k0, k1, c0, c1 = (int(x) for x in rng.integers(0, 2**32, 4))
        x0, x1 = threefry2x32_scalar(k0, k1, c0, c1)
        n0, n1 = draw_np(k0, k1, (c1 << 32) | c0)
        assert (x0, x1) == (int(n0), int(n1))


def test_native_threefry_matches_numpy():
    lib = native.get_lib()
    if lib is None:
        pytest.skip("native core unavailable")
    rng = np.random.default_rng(1)
    for _ in range(64):
        k0, k1 = (int(x) for x in rng.integers(0, 2**32, 2))
        counter = int(rng.integers(0, 2**64, dtype=np.uint64))
        v = lib.threefry_draw(k0, k1, counter)
        n0, n1 = draw_np(k0, k1, counter)
        assert v == (int(n1) << 32) | int(n0)


def test_native_timer_heap_ordering():
    lib = native.get_lib()
    if lib is None:
        pytest.skip("native core unavailable")
    heap = native.NativeTimerHeap(lib)
    heap.push(50, 0)
    heap.push(10, 1)
    heap.push(10, 2)   # same deadline: seq breaks the tie
    heap.push(30, 3)
    heap.cancel(1)
    assert heap.peek() == 10
    assert heap.pop_due(5) is None
    assert heap.pop_due(100) == 2   # 1 was cancelled
    assert heap.pop_due(100) == 3
    assert heap.pop_due(20) is None  # 50 not due yet
    assert heap.pop_due(50) == 0
    assert heap.pop_due(100) is None


def _trace_with_native(flag: str) -> str:
    """Run a chaos simulation in a subprocess with MADSIM_NATIVE=flag."""
    code = r"""
import os, sys
import madsim_tpu as ms
from madsim_tpu import task, time, rand

async def main():
    h = ms.Handle.current()
    trace = []
    async def worker(i):
        for k in range(20):
            await time.sleep(rand.thread_rng().gen_range_f64(0.001, 0.05))
            trace.append((i, k, time.monotonic_ns()))
    for i in range(5):
        h.create_node(name=f"n{i}", init=lambda i=i: worker(i))
    await time.sleep(2.0)
    return trace

print(hash(tuple(ms.run(main(), seed=1234))))
"""
    import os

    env = dict(os.environ, MADSIM_NATIVE=flag)
    out = subprocess.run([sys.executable, "-c", code], capture_output=True,
                         text=True, env=env, timeout=120)
    assert out.returncode == 0, out.stderr
    return out.stdout.strip()


def test_native_and_python_backends_bit_identical():
    """The native core is an accelerator, not a semantic fork: the same seed
    must give the identical event trace with the native core on and off."""
    if native.get_lib() is None:
        pytest.skip("native core unavailable")
    assert _trace_with_native("1") == _trace_with_native("0")


def test_native_poll_loop_bit_identical_to_python_loop():
    """The C run_all_ready must walk the exact trajectory of the Python
    loop: same results, same poll counts, same final virtual clocks over a
    chaos workload (the native loop is an accelerator, never a fork)."""
    import madsim_tpu as ms
    from madsim_tpu import task as mtask, time as vtime
    from madsim_tpu.net import Endpoint, NetSim, rpc

    if ms.Runtime(seed=0).task._native_ready is None:
        pytest.skip("native core not built")

    class Ping:
        def __init__(self, n):
            self.n = n

    async def world():
        h = ms.Handle.current()

        async def srv_init():
            ep = await Endpoint.bind("10.0.0.1:1")

            async def handle(req):
                return Ping(req.n + 10)

            rpc.add_rpc_handler(ep, Ping, handle)
            await vtime.sleep(1e6)

        srv = h.create_node(name="s", ip="10.0.0.1", init=srv_init)
        cli = h.create_node(name="c", ip="10.0.0.2")

        async def chaos():
            await vtime.sleep(0.4)
            h.pause(srv)
            await vtime.sleep(0.2)
            h.resume(srv)
            h.restart(srv)

        mtask.spawn(chaos())

        async def client():
            ep = await Endpoint.bind("10.0.0.2:0")
            ok = 0
            for i in range(5):
                try:
                    r = await rpc.call(ep, "10.0.0.1:1", Ping(i), timeout=0.5)
                    ok += r.n
                except Exception:
                    await vtime.sleep(0.05)
            return ok

        return await cli.spawn(client())

    def run(force_python):
        out = []
        for seed in range(6):
            rt = ms.Runtime(seed=seed)
            if force_python:
                rt.task._native_ready = None
            out.append((rt.block_on(world()), rt.task.poll_count,
                        rt.handle.time.elapsed_ns))
        return out

    assert run(False) == run(True)
