"""Coverage-guided fault-schedule search (madsim_tpu/search/).

The closed-fuzzer-loop contract (docs/search.md):

- the guided sweep is BITWISE reproducible — identical across re-runs
  and across ``pipeline=True/False`` (the mutation lanes are counter-
  based splitmix64, the corpus fold is sequential and deterministic);
- guided search measurably beats the matched random-mutation baseline
  on the conjunction family (the staircase argument);
- corpus + per-slot schedule state survives checkpoint→resume
  bit-exactly through the PR 7 aux-array channel;
- ``search=None`` sweeps compile the exact pre-search programs (the
  guided run reuses the same superstep runners — only NEW cache entries
  appear, keyed separately);
- zero added host syncs: corpus telemetry rides the retire pulls the
  loop already pays (counted through the ``_fetch`` hook);
- a chaotic guided fleet equals a clean one bitwise;
- ``DeviceEngine.refill`` takes first-class per-slot ``(W, F, 4)``
  schedules — device arrays with no host sync — with dim errors naming
  both dims.

Compile budget: every sweep here shares ONE module-scoped family engine
and the same (batch_worlds=32, chunk_steps=32) shapes, so the jit and
persistent caches amortize across the whole file.
"""
import importlib
import os
import tempfile

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from madsim_tpu.engine import DeviceEngine
from madsim_tpu.engine.checkpoint import CheckpointError
from madsim_tpu.search import (
    EMPTY_NOVELTY,
    GuidedPairActor,
    GuidedPairConfig,
    SearchConfig,
    corpus_init,
    engine_config,
    family_schedule,
)
from madsim_tpu.search.family import HUNT_NODES, HUNT_ROWS, hunt_search_config

sweep_mod = importlib.import_module("madsim_tpu.parallel.sweep")
sweep = sweep_mod.sweep

# Shared sweep shapes (see module docstring).
BATCH = dict(recycle=True, batch_worlds=32, chunk_steps=32)


@pytest.fixture(scope="module")
def hunt():
    """One family engine for the whole file (jit caches are
    per-instance; rebuilding would recompile every program)."""
    acfg = GuidedPairConfig(n=HUNT_NODES)
    cfg = engine_config(acfg)
    eng = DeviceEngine(GuidedPairActor(acfg), cfg)
    tmpl = family_schedule(HUNT_ROWS, acfg)
    return eng, cfg, tmpl


def _guided(eng, cfg, tmpl, n_seeds, guided=True,
            max_steps=10_000_000, **kw):
    return sweep(None, cfg, np.arange(n_seeds), engine=eng, faults=tmpl,
                 max_steps=max_steps, search=hunt_search_config(guided),
                 **BATCH, **kw)


# ---------------------------------------------------------------------------
# splitmix64 lanes: device == host, counter-based
# ---------------------------------------------------------------------------

def test_splitmix_device_matches_host_fleet_prng():
    """The device lanes are bit-identical to the fleet fabric's host
    splitmix64 applied at offset counters — one PRNG definition across
    the repo (fleet/rpc.py is the reference)."""
    from madsim_tpu.fleet.rpc import splitmix64 as host_mix
    from madsim_tpu.search.rng import _u32, lanes_u32, splitmix64_dev

    mask = (1 << 64) - 1
    for x in (0, 1, 0xDEADBEEFCAFEBABE, mask, 1234567890123456789):
        hi, lo = splitmix64_dev((_u32((x >> 32) & 0xFFFFFFFF),
                                 _u32(x & 0xFFFFFFFF)))
        assert ((int(hi) << 32) | int(lo)) == host_mix(x)
    gamma = 0x9E3779B97F4A7C15
    x0 = (jnp.uint32(0x12345678), jnp.uint32(0x9ABCDEF0))
    lanes = np.asarray(lanes_u32(x0, 9))
    base = (0x12345678 << 32) | 0x9ABCDEF0
    for i in range(9):
        assert int(lanes[i]) == host_mix((base + i * gamma) & mask) \
            & 0xFFFFFFFF


def test_lanes_are_pure_functions_of_seed_id_generation():
    from madsim_tpu.search.rng import lanes_u32, stream_key

    ids = jnp.arange(6, dtype=jnp.int32)
    a = np.asarray(lanes_u32(stream_key(7, ids, 3), 4))
    b = np.asarray(lanes_u32(stream_key(7, ids, 3), 4))
    c = np.asarray(lanes_u32(stream_key(7, ids, 4), 4))
    d = np.asarray(lanes_u32(stream_key(8, ids, 3), 4))
    assert (a == b).all()
    assert not (a == c).all() and not (a == d).all()
    # Distinct slots get distinct streams.
    assert len({tuple(r) for r in a}) == a.shape[0]


# ---------------------------------------------------------------------------
# Corpus: novelty scoring + sequential insertion
# ---------------------------------------------------------------------------

def test_corpus_novelty_and_harvest(hunt):
    from madsim_tpu.search.corpus import harvest_fold, novelty

    _eng, _cfg, tmpl = hunt
    corp = corpus_init(4, tmpl)
    # Template entry: sig 0, score 0, filled.
    assert int(np.asarray(corp.filled).sum()) == 1
    # Novelty against {sig 0}: the popcount of the candidate signature.
    assert int(novelty(jnp.uint32(0b1011), corp)) == 3
    assert int(novelty(jnp.uint32(0), corp)) == 0

    sched = jnp.broadcast_to(jnp.asarray(tmpl), (3,) + tmpl.shape)
    sigs = jnp.asarray([0b1011, 0b1011, 0], jnp.uint32)
    mask = jnp.asarray([True, True, True])
    corp2, n_ins = harvest_fold(corp, sched, sigs, mask, min_novelty=1)
    # World 0 inserts (novel); world 1 is now distance 0 to it — skipped;
    # world 2 is distance 0 to the template — skipped.
    assert int(n_ins) == 1
    assert int(np.asarray(corp2.filled).sum()) == 2
    assert int(np.asarray(corp2.inserted)) == 1
    # Empty corpus scores EMPTY_NOVELTY.
    empty = corp._replace(filled=jnp.zeros((4,), bool))
    assert int(novelty(jnp.uint32(1), empty)) == EMPTY_NOVELTY


def test_children_valid_and_keyed_by_generation(hunt):
    from madsim_tpu.search.mutate import make_children

    eng, cfg, tmpl = hunt
    scfg = hunt_search_config(True)
    corp = corpus_init(8, tmpl)
    ids = jnp.arange(16, dtype=jnp.int32)
    c1 = np.asarray(make_children(scfg, cfg, corp, ids, jnp.int32(1)))
    c1b = np.asarray(make_children(scfg, cfg, corp, ids, jnp.int32(1)))
    c2 = np.asarray(make_children(scfg, cfg, corp, ids, jnp.int32(2)))
    assert (c1 == c1b).all() and not (c1 == c2).all()
    en = c1[..., 0] >= 0
    assert (c1[en][:, 1] >= 0).all() and (c1[en][:, 1] <= 9).all()
    node_op = (c1[en][:, 1] <= 5) | (c1[en][:, 1] >= 8)
    assert (c1[en][node_op][:, 2:] >= 0).all()
    assert (c1[en][node_op][:, 2:] < cfg.n_nodes).all()
    # Disabled rows are canonical DISABLED_ROW sentinels.
    assert (c1[~en] == np.array([-1, 0, 0, 0], np.int32)).all()


# ---------------------------------------------------------------------------
# The guided sweep: determinism, the staircase gap, triage hand-off
# ---------------------------------------------------------------------------

def test_guided_sweep_bitwise_rerun_and_pipeline(hunt):
    eng, cfg, tmpl = hunt
    a = _guided(eng, cfg, tmpl, 128, stop_on_first_bug=True)
    b = _guided(eng, cfg, tmpl, 128, stop_on_first_bug=True)
    c = _guided(eng, cfg, tmpl, 128, stop_on_first_bug=True,
                pipeline=False)
    assert a.failing_seeds, "the guided hunt must reach the bug"
    for other in (b, c):
        assert (a.bug == other.bug).all()
        for k in a.observations:
            np.testing.assert_array_equal(
                np.asarray(a.observations[k]),
                np.asarray(other.observations[k]), err_msg=k)
        assert (a.search.schedules == other.search.schedules).all()
        assert (a.search.corpus_sched == other.search.corpus_sched).all()
        assert (a.search.corpus_sig == other.search.corpus_sig).all()
        assert a.search.generations == other.search.generations
        assert a.search.inserted == other.search.inserted
        np.testing.assert_array_equal(a.coverage.hits, other.coverage.hits)


def test_guided_beats_random_on_the_family(hunt):
    """The acceptance gate's core claim at test scale: on the
    conjunction family, guided search reaches the bug inside a budget
    the matched random-mutation baseline cannot (the full measured gap
    — ~73 vs ~409 seeds — is `bench.py guided_hunt` / `make
    fuzz-demo`)."""
    eng, cfg, tmpl = hunt
    g = _guided(eng, cfg, tmpl, 128, stop_on_first_bug=True)
    r = _guided(eng, cfg, tmpl, 128, guided=False, stop_on_first_bug=True)
    assert g.failing_seeds, "guided search missed the bug in budget"
    assert not r.failing_seeds, \
        "random baseline found the bug inside the guided budget — the " \
        "family lost its staircase gap (retune search/family.py)"
    # The novelty curve actually grew: feedback is flowing.
    assert g.search.corpus_size > 1
    assert g.coverage.novelty_curve[-1] > 1


def test_guided_find_triages_to_the_two_target_restarts(hunt):
    """Every find pipes unchanged through triage: the materialized
    per-seed schedule lands in triage_ctx, ddmin converges to exactly
    the two target restarts, 1-minimal."""
    eng, cfg, tmpl = hunt
    res = _guided(eng, cfg, tmpl, 128, stop_on_first_bug=True)
    s0 = res.failing_seeds[0]
    # The materialized schedule is what the failing world actually ran.
    assert res.search.schedules.shape[1:] == tmpl.shape
    assert res.triage_ctx.faults is res.search.schedules
    mr = res.minimize(chunk_steps=32, max_steps=20_000)
    assert mr.seed == s0
    assert mr.final_rows == 2 and mr.one_minimal
    acfg = GuidedPairConfig(n=HUNT_NODES)
    assert sorted(int(x) for x in mr.schedule[:, 2]) == \
        [acfg.node_a, acfg.node_b]


def test_search_validation_errors(hunt):
    eng, cfg, tmpl = hunt
    scfg = hunt_search_config(True)
    with pytest.raises(ValueError, match="recycle=True"):
        sweep(None, cfg, np.arange(8), engine=eng, faults=tmpl,
              chunk_steps=32, max_steps=256, search=scfg)
    with pytest.raises(ValueError, match="fault-schedule template"):
        sweep(None, cfg, np.arange(8), engine=eng, max_steps=256,
              search=scfg, **BATCH)
    acfg = GuidedPairConfig(n=HUNT_NODES)
    import dataclasses as dc

    eng_off = DeviceEngine(GuidedPairActor(acfg),
                           dc.replace(cfg, metrics=False))
    with pytest.raises(ValueError, match="metrics=True"):
        sweep(None, eng_off.cfg, np.arange(8), engine=eng_off,
              faults=tmpl, max_steps=256, search=scfg, **BATCH)
    with pytest.raises(ValueError, match="min_novelty"):
        SearchConfig(min_novelty=0)
    with pytest.raises(ValueError, match="cumulative"):
        SearchConfig(disable_pct=60, time_pct=60)


# ---------------------------------------------------------------------------
# Checkpoint → resume: the corpus survives bit-exactly (aux channel)
# ---------------------------------------------------------------------------

def test_guided_checkpoint_resume_bit_exact(hunt, tmp_path):
    eng, cfg, tmpl = hunt
    seeds_n = 96
    unbroken = _guided(eng, cfg, tmpl, seeds_n)
    path = str(tmp_path / "guided.npz")
    _part = _guided(eng, cfg, tmpl, seeds_n, max_steps=64 * 32,
                    checkpoint_path=path, checkpoint_every_chunks=4)
    full = _guided(eng, cfg, tmpl, seeds_n, checkpoint_path=path,
                   resume=True)
    assert (unbroken.bug == full.bug).all()
    for k in unbroken.observations:
        np.testing.assert_array_equal(
            np.asarray(unbroken.observations[k]),
            np.asarray(full.observations[k]), err_msg=k)
    assert (unbroken.search.schedules == full.search.schedules).all()
    assert (unbroken.search.corpus_sched == full.search.corpus_sched).all()
    assert (unbroken.search.corpus_sig == full.search.corpus_sig).all()
    assert (unbroken.search.corpus_score == full.search.corpus_score).all()
    assert unbroken.search.generations == full.search.generations
    assert unbroken.search.inserted == full.search.inserted
    np.testing.assert_array_equal(unbroken.coverage.hits,
                                  full.coverage.hits)


def test_guided_plain_checkpoint_mixups_refused(hunt, tmp_path):
    eng, cfg, tmpl = hunt
    path = str(tmp_path / "guided.npz")
    _guided(eng, cfg, tmpl, 96, max_steps=64 * 32, checkpoint_path=path,
            checkpoint_every_chunks=4)
    # Guided checkpoint, plain resume: refused with a pointed error.
    with pytest.raises(CheckpointError, match="guided"):
        sweep(None, cfg, np.arange(96), engine=eng, faults=tmpl,
              max_steps=10_000_000, checkpoint_path=path, resume=True,
              **BATCH)
    # Plain checkpoint, guided resume: refused too.
    plain = str(tmp_path / "plain.npz")
    sweep(None, cfg, np.arange(96), engine=eng, faults=tmpl,
          max_steps=64 * 32, checkpoint_path=plain,
          checkpoint_every_chunks=4, **BATCH)
    with pytest.raises(CheckpointError, match="plain"):
        _guided(eng, cfg, tmpl, 96, checkpoint_path=plain, resume=True)


# ---------------------------------------------------------------------------
# Sync discipline + compile identity
# ---------------------------------------------------------------------------

def test_guided_sweep_adds_zero_host_syncs(hunt, monkeypatch):
    """Corpus syncs ride the existing cadence: every pull is either a
    per-superstep scalar fetch or a retire pull the plain recycled loop
    pays too — counted through the one sanctioned ``_fetch`` hook."""
    eng, cfg, tmpl = hunt
    calls = []
    real_fetch = sweep_mod._fetch

    def counting_fetch(tree):
        calls.append(1)
        return real_fetch(tree)

    monkeypatch.setattr(sweep_mod, "_fetch", counting_fetch)
    res = _guided(eng, cfg, tmpl, 96)
    st = res.loop_stats
    assert st["retire_fetches"] >= 1          # refills happened
    assert len(calls) == st["scalar_fetches"] + st["retire_fetches"] + 1


def test_search_none_compiles_exact_pre_search_programs(hunt):
    """A ``search=None`` sweep touches no search machinery: no searcher
    or schedule-tail programs are built, and its compaction programs are
    the ``with_sched=False`` variants. A guided sweep then REUSES the
    very same superstep cache entries (the chunk/superstep programs are
    untouched by search — its one new program lives under its own
    keys), so the op-budget ledger of the sweep programs is untouched by
    construction."""
    eng, cfg, tmpl = hunt
    eng.__dict__.pop("_searcher_cache", None)
    eng.__dict__.pop("_sched_tail_cache", None)
    # The module-scoped engine already ran guided sweeps: diff against
    # the pre-existing program sets instead of demanding emptiness.
    compact_pre = set(eng.__dict__.get("_compactor_cache", {}))
    plain = sweep(None, cfg, np.arange(96), engine=eng, faults=tmpl,
                  max_steps=10_000_000, **BATCH)
    assert plain.search is None
    assert "_searcher_cache" not in eng.__dict__
    assert "_sched_tail_cache" not in eng.__dict__
    new_compact = set(eng.__dict__["_compactor_cache"]) - compact_pre
    assert all(not k[-1] for k in new_compact)  # with_sched=False only
    sstep_keys = set(eng.__dict__["_sharded_superstep_cache"])
    _g = _guided(eng, cfg, tmpl, 96)
    # The guided run added search-keyed programs only — the superstep
    # runners it dispatched are the SAME cache entries the plain sweep
    # compiled.
    assert set(eng.__dict__["_sharded_superstep_cache"]) == sstep_keys
    assert eng.__dict__["_searcher_cache"]


# ---------------------------------------------------------------------------
# Fleet: chaotic guided fleet == clean guided fleet (bitwise)
# ---------------------------------------------------------------------------

def test_fleet_guided_chaotic_equals_clean(hunt):
    """The chaos-matrix leg under guided refill: kills/expiries cost
    wall time, never results. (Guided fleet results are deterministic
    per (seeds, range partitioning, SearchConfig) — each range evolves
    its own corpus, so fleet != single-host here by design; the
    invariance that matters is chaos-invariance, docs/search.md.)"""
    from madsim_tpu.fleet import fleet_sweep
    from madsim_tpu.fleet.chaos import ChaosConfig

    eng, cfg, tmpl = hunt
    seeds = np.arange(96)
    kw = dict(engine=eng, faults=tmpl, chunk_steps=32,
              max_steps=10_000_000, recycle=True, batch_worlds=32,
              search=hunt_search_config(True))
    clean = fleet_sweep(None, cfg, seeds, n_workers=2, range_size=48,
                        **kw)
    chaotic = fleet_sweep(None, cfg, seeds, n_workers=2, range_size=48,
                          chaos=ChaosConfig(seed=7, kill_at=(("w1", 2),),
                                            restart_after=2), **kw)
    assert (clean.bug == chaotic.bug).all()
    for k in clean.observations:
        np.testing.assert_array_equal(
            np.asarray(clean.observations[k]),
            np.asarray(chaotic.observations[k]), err_msg=k)


# ---------------------------------------------------------------------------
# DeviceEngine.refill: first-class per-slot schedules
# ---------------------------------------------------------------------------

def test_refill_per_slot_dim_validation_names_both_dims(hunt):
    eng, cfg, tmpl = hunt
    faults = np.broadcast_to(tmpl, (8,) + tmpl.shape).copy()
    st = eng.init(np.arange(8, dtype=np.uint64), faults=faults)
    st = eng.run_steps(st, 64)
    mask = np.zeros(8, bool)
    mask[2:5] = True
    seeds = np.arange(100, 108, dtype=np.uint64)
    with pytest.raises(ValueError, match=r"leading dim 5.*8 slots"):
        eng.refill(st, mask, seeds, faults=faults[:5])
    with pytest.raises(ValueError, match=r"leading dim 5.*8 slots"):
        eng.refill(st, mask, seeds, faults=jnp.asarray(faults[:5]))
    with pytest.raises(ValueError, match="per-slot"):
        eng.refill(st, mask, seeds, faults=jnp.asarray(tmpl))


def test_refill_device_schedule_path_bitwise_equals_host(hunt):
    """The device (W, F, 4) override — the path the search generator
    feeds — initializes worlds bit-identically to the validated host
    path for the same values, with no host pull of the schedules."""
    eng, cfg, tmpl = hunt
    faults = np.broadcast_to(tmpl, (8,) + tmpl.shape).copy()
    faults[4:, 0, 2] = 1
    mask = np.zeros(8, bool)
    mask[2:5] = True
    seeds = np.arange(100, 108, dtype=np.uint64)

    st_a = eng.init(np.arange(8, dtype=np.uint64), faults=faults)
    st_a = eng.run_steps(st_a, 64)
    st_b = eng.init(np.arange(8, dtype=np.uint64), faults=faults)
    st_b = eng.run_steps(st_b, 64)
    host = eng.refill(st_a, mask, seeds, faults=faults)
    dev = eng.refill(st_b, mask, seeds, faults=jnp.asarray(faults))
    oh, od = jax.device_get((eng.observe_device(host),
                             eng.observe_device(dev)))
    for k in oh:
        np.testing.assert_array_equal(np.asarray(oh[k]),
                                      np.asarray(od[k]), err_msg=k)
