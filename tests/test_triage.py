"""Failure triage: batched ddmin minimizer + deduplicated corpus
(madsim_tpu/triage/, docs/triage.md).

The load-bearing contracts pinned here:

- ddmin CONVERGENCE on a known-minimal case: a synthetic actor whose
  bug requires exactly rows {5, 20} of a 32-row schedule minimizes to
  exactly those two rows, 1-minimal (every single-row drop verified to
  stop failing).
- DETERMINISM: re-running yields a bitwise-identical minimized schedule
  and identical round history; pipelined and serial candidate sweeps
  agree bitwise.
- BATCHING: each round's candidate evaluation is ONE sweep (counted
  through both the sweep-call seam and the parallel.sweep ``_fetch``
  hook) — never a per-candidate loop.
- CORPUS: k injected distinct failure classes dedupe to exactly k
  entries, keyed by the device-parity behavior signature; each class's
  minimized bundle round-trips through obs/bundle.py and replays to the
  recorded failure.
- HOST TWIN: MADSIM_MINIMIZE ddmins the fault-model knob rows of a
  failing ``@madsim_tpu.test`` before bundling.
"""
import importlib
import json
import os

import numpy as np
import pytest

# The package re-exports the sweep FUNCTION as an attribute named like
# the submodule; resolve the module itself for the monkeypatch seams.
sweep_mod = importlib.import_module("madsim_tpu.parallel.sweep")
from madsim_tpu.engine import DeviceEngine
from madsim_tpu.engine.core import FAULT_KILL, FAULT_PAUSE, FAULT_SET_LOSS
from madsim_tpu.parallel.sweep import sweep
from madsim_tpu.triage import (
    FailureClass,  # noqa: F401  (public-surface import check)
    MinimizeResult,
    PairRestartActor,
    PairRestartConfig,
    TriageError,
    behavior_signatures,
    failure_classes,
    minimize,
    minimize_rows,
    pair_schedule,
    triage,
)
from madsim_tpu.triage import shrink
from madsim_tpu.triage.synthetic import engine_config

ACFG = PairRestartConfig()


@pytest.fixture(scope="module")
def pair_eng():
    return DeviceEngine(PairRestartActor(ACFG), engine_config(ACFG))


@pytest.fixture(scope="module")
def pair_eng_m():
    return DeviceEngine(PairRestartActor(ACFG),
                        engine_config(ACFG, metrics=True))


MIN_KW = dict(chunk_steps=32, max_steps=4_000)


# ---------------------------------------------------------------------------
# the schedule algebra (shrink.py) — pure host-side units
# ---------------------------------------------------------------------------

def test_shrink_candidates_and_cost_order():
    rows = np.array([[1_000, FAULT_KILL, 1, 0],
                     [2_000, FAULT_SET_LOSS, 500_000, 0],
                     [3_000, FAULT_PAUSE, 2, 0]], np.int32)
    # Subsets at k=2: two keep-chunks, no complements (they coincide).
    pairs = shrink.subset_candidates(rows, 2)
    assert [p[0] for p in pairs] == ["subset:0/2", "subset:1/2"]
    # k=3 adds the complements — exactly the single-row drops.
    pairs = shrink.subset_candidates(rows, 3)
    assert sum(p[0].startswith("complement") for p in pairs) == 3
    # Weakenings: kill->pause and loss->0, canonical order + strictly
    # cheaper under the total cost order.
    weak = shrink.weaken_candidates(rows)
    assert [w[0] for w in weak] == ["weaken:0:kill->pause",
                                   "weaken:1:loss->0"]
    for _label, cand in weak:
        assert shrink.schedule_cost(cand) < shrink.schedule_cost(rows)
    # Tightening halves fire times, strictly cheaper too.
    tight = shrink.tighten_candidates(rows)
    assert len(tight) == 3
    assert int(tight[0][1][0, 0]) == 500
    # Dropping rows dominates everything: fewest-rows-first.
    dropped = shrink.keep_rows(rows, np.array([0]))
    assert shrink.schedule_cost(dropped) < shrink.schedule_cost(weak[0][1])
    # Normalization canonicalizes disabled rows (bitwise tie-break).
    messy = rows.copy()
    messy[1] = [-7, 3, 9, 9]
    assert (shrink.normalize(messy)[1] == shrink.DISABLED_ROW).all()


def test_minimize_rows_weaken_phase_pure_oracle():
    """The generic loop adopts a severity weakening when dropping the
    row is impossible: oracle = 'fails iff row 0 is live with op KILL
    or PAUSE' -> ddmin keeps row 0, weaken turns KILL into PAUSE."""
    rows = np.array([[1_000, FAULT_KILL, 1, 0],
                     [2_000, FAULT_KILL, 2, 0]], np.int32)

    def evaluate(cands):
        return np.array([c[0, 0] >= 0 and int(c[0, 1]) in
                         (FAULT_KILL, FAULT_PAUSE) for c in cands], bool)

    final, stats = minimize_rows(rows, evaluate, weaken=True)
    live = shrink.compact(final)
    assert live.shape == (1, 4)
    assert int(live[0, 1]) == FAULT_PAUSE
    assert stats["weakenings"] == ["weaken:0:kill->pause"]
    assert stats["one_minimal"]


def test_minimize_rows_rejects_non_failing():
    rows = np.array([[1_000, FAULT_KILL, 1, 0]], np.int32)
    with pytest.raises(TriageError, match="does not fail"):
        minimize_rows(rows, lambda cands: np.zeros(len(cands), bool))


# ---------------------------------------------------------------------------
# batched device minimization (minimize.py)
# ---------------------------------------------------------------------------

def test_ddmin_converges_to_known_minimal_pair(pair_eng):
    """The acceptance case: bug needs exactly rows {5, 20} of a 32-row
    schedule -> the minimizer returns exactly those two rows and the
    1-minimality check passes (ground-truthed below by direct runs)."""
    rows = pair_schedule(n_rows=32, need=(5, 20), acfg=ACFG)
    res = minimize(None, pair_eng.cfg, 7, rows, engine=pair_eng, **MIN_KW)
    assert isinstance(res, MinimizeResult)
    assert res.original_rows == 32
    assert res.final_rows == 2
    assert (res.schedule == rows[[5, 20]]).all()
    assert res.one_minimal
    # Ground truth for the 1-minimality claim: each single row alone
    # does NOT fail, both together DO.
    for keep in ([5], [20], [5, 20]):
        obs = pair_eng.observe(pair_eng.run(
            pair_eng.init(np.asarray([7], np.uint64),
                          faults=rows[keep][None]), max_steps=4_000))
        assert bool(obs["bug"][0]) == (keep == [5, 20])
    # Provenance block: the bundle schema the corpus embeds.
    prov = res.provenance()
    assert prov["schema"] == "madsim.triage.minimization/1"
    assert (prov["original_rows"], prov["final_rows"]) == (32, 2)
    assert prov["rounds"] == res.rounds > 3
    assert prov["candidates_evaluated"] == res.candidates_evaluated \
        > res.rounds  # batched: strictly more candidates than sweeps
    assert prov["one_minimal"] is True


def test_minimize_bitwise_deterministic_and_pipeline_agnostic(pair_eng):
    """Determinism gate: same (seed, schedule) -> bitwise-identical
    minimized schedule across two runs AND across pipeline=True/False,
    with identical round histories."""
    rows = pair_schedule(n_rows=16, need=(3, 12), acfg=ACFG)
    runs = [minimize(None, pair_eng.cfg, 11, rows, engine=pair_eng,
                     pipeline=p, **MIN_KW)
            for p in (True, True, False)]
    a, b, c = runs
    assert (a.full == b.full).all() and (a.full == c.full).all()
    assert (a.schedule == b.schedule).all()
    assert a.rounds == b.rounds == c.rounds
    assert a.candidates_evaluated == b.candidates_evaluated \
        == c.candidates_evaluated
    assert a.history == b.history == c.history
    assert (a.schedule == rows[[3, 12]]).all()


def test_each_round_is_one_sweep_no_per_candidate_loop(pair_eng,
                                                       monkeypatch):
    """BATCHING contract: candidate evaluation dispatches ONE sweep per
    round — counted at the sweep-call seam AND via the parallel.sweep
    ``_fetch`` hook (host pulls must scale with rounds, not with the
    candidate count)."""
    sweep_calls = []
    real_sweep = sweep_mod.sweep

    def counting_sweep(actor, cfg, seeds, **kw):
        sweep_calls.append(len(np.asarray(seeds)))
        return real_sweep(actor, cfg, seeds, **kw)

    fetches = []
    real_fetch = sweep_mod._fetch

    def counting_fetch(tree):
        fetches.append(1)
        return real_fetch(tree)

    monkeypatch.setattr(sweep_mod, "sweep", counting_sweep)
    monkeypatch.setattr(sweep_mod, "_fetch", counting_fetch)

    rows = pair_schedule(n_rows=32, need=(5, 20), acfg=ACFG)
    res = minimize(None, pair_eng.cfg, 7, rows, engine=pair_eng, **MIN_KW)
    # One sweep per round, every candidate of the round inside it.
    assert len(sweep_calls) == res.rounds
    assert sum(sweep_calls) >= res.candidates_evaluated
    # Host pulls scale with rounds (a few per sweep: scalar batches +
    # the final merge), NEVER with the candidate count.
    assert len(fetches) <= 8 * res.rounds
    assert res.candidates_evaluated > res.rounds  # batching was real


def test_schedule_independent_failure_minimizes_to_empty(pair_eng):
    """A bug that fires regardless of the schedule short-circuits to
    zero rows in the first round (the 'empty' probe)."""

    class AlwaysBug(PairRestartActor):
        def invariant(self, cfg, s):
            return s["restarts"][..., 0] >= 0  # tautology

    eng = DeviceEngine(AlwaysBug(ACFG), engine_config(ACFG))
    rows = pair_schedule(n_rows=4, need=(0, 3), acfg=ACFG)
    res = minimize(None, eng.cfg, 3, rows, engine=eng, **MIN_KW)
    assert res.final_rows == 0
    assert res.one_minimal
    assert res.rounds == 2  # verify-original (+empty) and verify-1min


def test_minimize_rejects_non_failing_seed(pair_eng):
    # Schedule lacking the node_b restart: never fails.
    rows = pair_schedule(n_rows=8, need=(1, 6), acfg=ACFG)
    rows[6, 2] = 0
    with pytest.raises(TriageError, match="does not fail"):
        minimize(None, pair_eng.cfg, 7, rows, engine=pair_eng, **MIN_KW)


def test_tighten_phase_halves_times_deterministically(pair_eng):
    """Opt-in fire-time tightening: the pair bug is time-insensitive,
    so tightening walks both surviving rows' times to 0 — still
    failing, still 2 rows, bitwise reproducible."""
    rows = pair_schedule(n_rows=4, need=(0, 3), acfg=ACFG,
                         t0_us=4, dt_us=4)
    res = minimize(None, pair_eng.cfg, 5, rows, engine=pair_eng,
                   tighten=True, **MIN_KW)
    res2 = minimize(None, pair_eng.cfg, 5, rows, engine=pair_eng,
                    tighten=True, **MIN_KW)
    assert res.final_rows == 2
    assert (res.schedule[:, 0] == 0).all()
    assert [w.startswith("tighten:") for w in res.weakenings].count(True) \
        == len(res.weakenings) > 0
    assert (res.full == res2.full).all()
    assert res.history == res2.history


def test_sweep_result_minimize_roundtrip(pair_eng):
    """SweepResult.minimize(seed) slices the per-world schedule and
    reuses the sweep's engine; equals a direct triage.minimize call."""
    n = 8
    rows = pair_schedule(n_rows=8, need=(1, 6), acfg=ACFG)
    faults = np.broadcast_to(rows, (n, 8, 4)).copy()
    faults[1::2, 6, 2] = 0  # odd seeds: decoy schedules, must pass
    res = sweep(None, pair_eng.cfg, np.arange(n), faults=faults,
                engine=pair_eng, chunk_steps=32, max_steps=4_000)
    assert res.failing_seeds == [0, 2, 4, 6]
    mr = res.minimize(**MIN_KW)           # defaults to first failing seed
    direct = minimize(None, pair_eng.cfg, 0, rows, engine=pair_eng,
                      **MIN_KW)
    assert mr.seed == 0
    assert (mr.full == direct.full).all()
    assert (mr.schedule == rows[[1, 6]]).all()
    with pytest.raises(TriageError, match="not part of this sweep"):
        res.minimize(seed=999, **MIN_KW)


def test_merged_results_carry_no_triage_ctx():
    """Fleet-merged / reconstructed SweepResults must refuse to
    minimize with a pointed error instead of recomputing nonsense."""
    from madsim_tpu.parallel.sweep import SweepResult

    bare = SweepResult(seeds=np.arange(2, dtype=np.uint64),
                       bug=np.array([True, False]),
                       observations={"bug": np.array([True, False])},
                       steps_run=0, n_devices=1)
    assert bare.triage_ctx is None
    with pytest.raises(TriageError, match="no triage context"):
        bare.minimize()


# ---------------------------------------------------------------------------
# corpus dedup + bundles (corpus.py)
# ---------------------------------------------------------------------------

def _k_class_sweep(eng, n=24):
    """A sweep with exactly 3 distinct failure classes: per-world
    schedules of 2 / 4 / 8 live restart rows (all containing the pair),
    whose power-of-two fault_hist buckets differ."""
    F = 8
    faults = np.full((n, F, 4), -1, np.int32)
    for w in range(n):
        k = (2, 4, 8)[w % 3]
        faults[w, :k] = pair_schedule(n_rows=k, need=(0, k - 1), acfg=ACFG)
    return sweep(None, eng.cfg, np.arange(n), faults=faults, engine=eng,
                 chunk_steps=32, max_steps=4_000), faults


def test_k_injected_classes_dedupe_to_exactly_k(pair_eng_m):
    res, _faults = _k_class_sweep(pair_eng_m)
    assert len(res.failing_seeds) == 24
    classes = failure_classes(res)
    assert len(classes) == 3          # k classes -> exactly k entries
    assert [c.representative for c in classes] == [0, 1, 2]
    assert sorted(sum((list(c.seeds) for c in classes), [])) \
        == list(range(24))
    assert all(c.invariant_id == "pair_restart_conjunction"
               for c in classes)
    # Deterministic: identical keys on a re-run of the same sweep.
    res2, _ = _k_class_sweep(pair_eng_m)
    assert [c.key for c in failure_classes(res2)] \
        == [c.key for c in classes]


def test_corpus_signature_matches_device_behavior_signature(pair_eng_m):
    """Host-side corpus signatures equal the device coverage fold's
    behavior_signature bit for bit (same columns, bucketing, FNV)."""
    import jax.numpy as jnp

    from madsim_tpu.obs.coverage import behavior_signature
    from madsim_tpu.obs.metrics import MetricsBlock

    res, _faults = _k_class_sweep(pair_eng_m)
    per_seed = res.metrics["per_seed"]
    host = behavior_signatures(per_seed)
    mb = MetricsBlock(**{f: jnp.asarray(per_seed[f])
                         for f in MetricsBlock._fields})
    dev = np.asarray(behavior_signature(mb))
    assert (host == dev).all()


def test_triage_requires_metrics(pair_eng):
    res = sweep(None, pair_eng.cfg, np.arange(4),
                faults=pair_schedule(n_rows=4, need=(0, 3), acfg=ACFG),
                engine=pair_eng, chunk_steps=32, max_steps=4_000)
    with pytest.raises(ValueError, match="metrics=True"):
        failure_classes(res)


def test_triage_emits_minimized_bundles_that_replay(pair_eng_m, tmp_path):
    """triage(): one bundle per class, carrying the MINIMIZED rows and
    the minimization provenance block; replaying the bundle's schedule
    through the engine reproduces the recorded failure (the CLI leg of
    this contract runs in `make triage-demo`)."""
    from madsim_tpu.obs.bundle import load_bundle

    res, _faults = _k_class_sweep(pair_eng_m)
    report = triage(res, out_dir=str(tmp_path), **MIN_KW)
    assert len(report.classes) == len(report.bundles) == 3
    for fc in report.classes:
        mr = report.minimized[fc.key]
        assert mr.final_rows == 2 and mr.one_minimal
        bundle = load_bundle(report.bundles[fc.key])
        assert bundle["kind"] == "device_sweep"
        assert bundle["actor"] == "pair_restart"
        assert bundle["seed"] == fc.representative
        assert np.asarray(bundle["faults"]).shape == (2, 4)
        assert (np.asarray(bundle["faults"], np.int32)
                == mr.schedule).all()
        block = bundle["minimization"]
        assert block["schema"] == "madsim.triage.minimization/1"
        assert block["final_rows"] == 2
        assert block["rounds"] >= 1 and block["candidates_evaluated"] >= 2
        assert bundle["extra"]["failure_class"] == fc.key
        assert bundle["extra"]["n_seeds"] == fc.count
        # Library-level replay: the minimized schedule reproduces the
        # recorded failure on a fresh engine from the bundle's configs.
        from madsim_tpu.obs.cli import _actor_registry

        actor_cls, acfg_cls = _actor_registry()[bundle["actor"]]
        eng = DeviceEngine(
            actor_cls(acfg_cls(**bundle["actor_config"])),
            type(pair_eng_m.cfg)(**bundle["engine_config"]))
        trace = eng.trace(bundle["seed"], max_steps=256,
                          faults=np.asarray(bundle["faults"], np.int32))
        assert any(e.get("bug_raised") for e in trace)


def test_triage_minimize_false_buckets_only(pair_eng_m, tmp_path):
    res, faults = _k_class_sweep(pair_eng_m)
    report = triage(res, out_dir=str(tmp_path), minimize=False)
    assert report.minimized == {}
    from madsim_tpu.obs.bundle import load_bundle

    b = load_bundle(report.bundles[report.classes[0].key])
    assert b["minimization"] is None
    # The un-minimized bundle records the representative's ORIGINAL rows.
    assert np.asarray(b["faults"]).shape[0] == 2  # class 0: 2 live rows


# ---------------------------------------------------------------------------
# sweep validation satellite (per-world schedule dims)
# ---------------------------------------------------------------------------

def test_per_world_faults_leading_dim_names_both_dims(pair_eng):
    """(m, F, 4) with m != len(seeds) must fail at the API boundary
    naming BOTH dims — never silently gather wrong-world schedules."""
    rows = pair_schedule(n_rows=4, need=(0, 3), acfg=ACFG)
    for m in (5, 24):
        with pytest.raises(ValueError) as ei:
            sweep(None, pair_eng.cfg, np.arange(12),
                  faults=np.broadcast_to(rows, (m, 4, 4)).copy(),
                  engine=pair_eng, max_steps=64)
        assert f"leading dim {m}" in str(ei.value)
        assert "len(seeds)=12" in str(ei.value)


# ---------------------------------------------------------------------------
# host twin: MADSIM_MINIMIZE (testing.py)
# ---------------------------------------------------------------------------

def test_madsim_minimize_keeps_only_load_bearing_knob(monkeypatch,
                                                      tmp_path, capsys):
    """A @test failing IFF packet loss is on, run with three non-default
    fault-model knobs: MADSIM_MINIMIZE ddmins the knob rows to exactly
    the loss knob; the banner logs the row-count reduction and the
    bundle gains the minimization block."""
    import madsim_tpu as ms
    from madsim_tpu import time as simtime
    from madsim_tpu.net import Endpoint

    monkeypatch.setenv("MADSIM_MINIMIZE", "1")
    monkeypatch.setenv("MADSIM_REPRO_DIR", str(tmp_path))
    cfg = ms.Config()
    cfg.net.packet_loss_rate = 1.0             # the load-bearing knob
    cfg.net.send_latency = (0.002, 0.020)      # irrelevant to the bug
    cfg.fs.io_latency = (0.001, 0.002)         # irrelevant to the bug

    @ms.test(seed=5, config=cfg)
    async def lossy_test():
        h = ms.Handle.current()
        n1 = h.create_node(name="tx", ip="10.0.0.1")
        n2 = h.create_node(name="rx", ip="10.0.0.2")

        async def sender():
            ep = await Endpoint.bind(("10.0.0.1", 1))
            await ep.send_to(("10.0.0.2", 1), 1, b"x")

        async def receiver():
            ep = await Endpoint.bind(("10.0.0.2", 1))
            await simtime.timeout(5.0, ep.recv_from(1))

        n1.spawn(sender())
        await n2.spawn(receiver())

    with pytest.raises(TimeoutError):
        lossy_test()
    err = capsys.readouterr().err
    assert "fault-model minimization (MADSIM_MINIMIZE): " \
           "3 knob row(s) -> 1" in err
    assert "failure needs: net.packet_loss_rate" in err
    bundles = os.listdir(tmp_path)
    assert len(bundles) == 1
    with open(tmp_path / bundles[0], encoding="utf-8") as f:
        bundle = json.load(f)
    block = bundle["minimization"]
    assert block["kind"] == "fault_model_knobs"
    assert block["kept_knobs"] == ["net.packet_loss_rate"]
    assert sorted(block["dropped_knobs"]) == ["fs.io_latency",
                                              "net.send_latency"]
    assert block["one_minimal"] is True
    assert block["minimized_config"]["net"]["packet_loss_rate"] == 1.0
    assert block["minimized_config"]["net"]["send_latency"] \
        == [0.001, 0.010]  # reset to the default model


def test_madsim_minimize_off_by_default(monkeypatch, tmp_path, capsys):
    import madsim_tpu as ms

    monkeypatch.delenv("MADSIM_MINIMIZE", raising=False)
    monkeypatch.setenv("MADSIM_REPRO_DIR", str(tmp_path))
    cfg = ms.Config()
    cfg.net.packet_loss_rate = 0.5

    @ms.test(seed=5, config=cfg)
    async def failing():
        raise AssertionError("boom")

    with pytest.raises(AssertionError):
        failing()
    err = capsys.readouterr().err
    assert "fault-model minimization" not in err
    with open(tmp_path / os.listdir(tmp_path)[0], encoding="utf-8") as f:
        assert json.load(f)["minimization"] is None
