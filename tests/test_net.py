"""Network simulator tests, mirroring the reference's inline suites
(`endpoint.rs:314-528`, `tcp/mod.rs:67-248`, `rpc.rs`, `udp.rs`)."""
import pytest

import madsim_tpu as ms
from madsim_tpu import net, sync, task, time
from madsim_tpu.net import Endpoint, NetSim, TcpListener, TcpStream, UdpSocket
from madsim_tpu.net import rpc as msrpc


def make_two_nodes(rt):
    n1 = rt.create_node(name="n1", ip="10.0.0.1")
    n2 = rt.create_node(name="n2", ip="10.0.0.2")
    return n1, n2


def test_send_recv_tag_matching_out_of_order():
    """Tag 2 sent later is received first (`endpoint.rs:314-351`)."""
    rt = ms.Runtime(seed=1)
    n1, n2 = make_two_nodes(rt)
    barrier = sync.Barrier(2)

    async def sender():
        ep = await Endpoint.bind(("10.0.0.1", 1))
        await barrier.wait()
        await ep.send_to(("10.0.0.2", 1), 1, b"\x01")
        await time.sleep(1.0)
        await ep.send_to(("10.0.0.2", 1), 2, b"\x02")

    async def receiver():
        ep = await Endpoint.bind(("10.0.0.2", 1))
        await barrier.wait()
        data, frm = await ep.recv_from(2)
        assert data == b"\x02" and frm == ("10.0.0.1", 1)
        data, frm = await ep.recv_from(1)
        assert data == b"\x01" and frm == ("10.0.0.1", 1)

    n1.spawn(sender())
    h = n2.spawn(receiver())

    async def main():
        await h

    rt.block_on(main())


def test_receiver_drop_rebuffers():
    """A timed-out recv must not swallow later messages
    (`endpoint.rs:353-387`)."""
    rt = ms.Runtime(seed=1)
    n1, n2 = make_two_nodes(rt)
    barrier = sync.Barrier(2)

    async def sender():
        ep = await Endpoint.bind(("10.0.0.1", 1))
        await barrier.wait()
        await ep.send_to(("10.0.0.2", 1), 1, b"\x01")

    async def receiver():
        ep = await Endpoint.bind(("10.0.0.2", 1))
        with pytest.raises(TimeoutError):
            await time.timeout(1.0, ep.recv_from(1))
        await barrier.wait()
        data, frm = await ep.recv_from(1)
        assert data == b"\x01"

    n1.spawn(sender())
    h = n2.spawn(receiver())

    async def main():
        await h

    rt.block_on(main())


def test_bind_rules():
    """Bind semantics (`endpoint.rs:412-456`): unspecified, loopback,
    ephemeral ports, wrong-IP rejection, port reuse after close."""
    rt = ms.Runtime(seed=1)
    node = rt.create_node(name="n", ip="10.0.0.1")

    async def main():
        ep = await Endpoint.bind("0.0.0.0:0")
        ip, port = ep.local_addr()
        assert ip == "0.0.0.0" and port != 0

        ep6 = await Endpoint.bind("[::]:0")
        ip, port = ep6.local_addr()
        assert ip == "::" and port != 0

        lo = await Endpoint.bind("127.0.0.1:0")
        assert lo.local_addr()[0] == "127.0.0.1"

        with pytest.raises(net.AddrNotAvailable):
            await Endpoint.bind("10.0.0.2:0")

        ep2 = await Endpoint.bind("10.0.0.1:100")
        assert ep2.local_addr() == ("10.0.0.1", 100)
        with pytest.raises(net.AddrInUse):
            await Endpoint.bind("10.0.0.1:100")
        ep2.close()
        await Endpoint.bind("10.0.0.1:100")  # port reusable after close

    h = node.spawn(main())

    async def waiter():
        await h

    rt.block_on(waiter())


def test_connect_send_recv():
    """Endpoint.connect round-trip (`endpoint.rs:493-528`)."""
    rt = ms.Runtime(seed=1)
    n1, n2 = make_two_nodes(rt)
    barrier = sync.Barrier(2)

    async def server():
        ep = await Endpoint.bind(("10.0.0.1", 1))
        assert ep.local_addr() == ("10.0.0.1", 1)
        await barrier.wait()
        data, frm = await ep.recv_from(1)
        assert data == b"ping"
        await ep.send_to(frm, 1, b"pong")

    async def client():
        await barrier.wait()
        ep = await Endpoint.connect(("10.0.0.1", 1))
        assert ep.peer_addr() == ("10.0.0.1", 1)
        await ep.send(1, b"ping")
        data = await ep.recv(1)
        assert data == b"pong"

    n1.spawn(server())
    h = n2.spawn(client())

    async def main():
        await h

    rt.block_on(main())


def test_packet_loss_drops_datagrams():
    cfg = ms.Config()
    cfg.net.packet_loss_rate = 1.0
    rt = ms.Runtime(seed=1, config=cfg)
    n1, n2 = make_two_nodes(rt)

    async def sender():
        ep = await Endpoint.bind(("10.0.0.1", 1))
        await ep.send_to(("10.0.0.2", 1), 1, b"x")

    async def receiver():
        ep = await Endpoint.bind(("10.0.0.2", 1))
        with pytest.raises(TimeoutError):
            await time.timeout(5.0, ep.recv_from(1))

    n1.spawn(sender())
    h = n2.spawn(receiver())

    async def main():
        await h

    rt.block_on(main())


def test_rpc_basic_and_with_data():
    rt = ms.Runtime(seed=1)
    n1, n2 = make_two_nodes(rt)

    class Ping:
        def __init__(self, x):
            self.x = x

    async def server():
        ep = await Endpoint.bind(("10.0.0.1", 1))

        async def on_ping(req, data):
            return f"pong-{req.x}", bytes(reversed(data))

        msrpc.add_rpc_handler_with_data(ep, Ping, on_ping)
        await time.sleep(60.0)

    async def client():
        await time.sleep(0.1)  # let server bind
        ep = await Endpoint.bind("0.0.0.0:0")
        resp, data = await msrpc.call_with_data(ep, ("10.0.0.1", 1), Ping(7), b"abc")
        assert resp == "pong-7"
        assert data == b"cba"
        resp = await msrpc.call(ep, ("10.0.0.1", 1), Ping(1), timeout=5.0)
        assert resp == "pong-1"

    n1.spawn(server())
    h = n2.spawn(client())

    async def main():
        await h

    rt.block_on(main())


def test_tcp_stream_basic():
    """TCP round-trip (`tcp/mod.rs:67-96`)."""
    rt = ms.Runtime(seed=1)
    n1, n2 = make_two_nodes(rt)

    async def server():
        listener = await TcpListener.bind("0.0.0.0:8080")
        stream, peer = await listener.accept()
        data = await stream.read_exact(4)
        assert data == b"ping"
        await stream.write_all(b"pong")

    async def client():
        await time.sleep(0.1)
        stream = await TcpStream.connect(("10.0.0.1", 8080))
        await stream.write_all(b"ping")
        assert await stream.read_exact(4) == b"pong"

    n1.spawn(server())
    h = n2.spawn(client())

    async def main():
        await h

    rt.block_on(main())


def test_tcp_partition_heal_resumes_delivery():
    """disconnect → sends time out at receiver → heal → queued data flushes
    (`tcp/mod.rs:98-172`). The partition-buffering semantics."""
    rt = ms.Runtime(seed=1)
    n1, n2 = make_two_nodes(rt)
    done = sync.Event()

    async def server():
        listener = await TcpListener.bind("0.0.0.0:9000")
        stream, _ = await listener.accept()
        assert await stream.read_exact(1) == b"a"
        # Partition starts now (client side clogged); nothing arrives.
        with pytest.raises(TimeoutError):
            await time.timeout(2.0, stream.read_exact(1))
        # After heal the buffered byte arrives.
        assert await time.timeout(60.0, stream.read_exact(1)) == b"b"
        done.set()

    async def client():
        await time.sleep(0.1)
        stream = await TcpStream.connect(("10.0.0.1", 9000))
        await stream.write_all(b"a")
        await time.sleep(0.5)
        sim = ms.simulator(NetSim)
        sim.disconnect2(n1.id, n2.id)
        await stream.write_all(b"b")  # queued across the partition
        await time.sleep(5.0)
        sim.connect2(n1.id, n2.id)
        await done.wait()

    n1.spawn(server())
    h = n2.spawn(client())

    async def main():
        await h

    rt.block_on(main())


def test_node_reset_gives_peer_eof():
    """Killing a node closes its connections; peer reads EOF
    (`tcp/mod.rs:174-206`)."""
    rt = ms.Runtime(seed=1)
    n1, n2 = make_two_nodes(rt)
    got_eof = sync.Event()

    async def server():
        listener = await TcpListener.bind("0.0.0.0:9001")
        stream, _ = await listener.accept()
        assert await stream.read_exact(1) == b"x"
        data = await stream.read()
        assert data == b"", "peer reset must read as EOF"
        got_eof.set()

    async def client():
        await time.sleep(0.1)
        stream = await TcpStream.connect(("10.0.0.1", 9001))
        await stream.write_all(b"x")
        await time.sleep(1.0)  # then this node gets killed by main

    n1.spawn(server())
    n2.spawn(client())

    async def main():
        await time.sleep(2.0)
        ms.Handle.current().kill(n2)
        await time.timeout(30.0, got_eof.wait())

    rt.block_on(main())


def test_connection_refused():
    rt = ms.Runtime(seed=1)
    n1, _ = make_two_nodes(rt)

    async def client():
        with pytest.raises(net.ConnectionRefused):
            await TcpStream.connect(("10.0.0.9", 1234))

    h = n1.spawn(client())

    async def main():
        await h

    rt.block_on(main())


def test_udp_socket():
    rt = ms.Runtime(seed=1)
    n1, n2 = make_two_nodes(rt)
    barrier = sync.Barrier(2)

    async def a():
        sock = await UdpSocket.bind(("10.0.0.1", 5000))
        await barrier.wait()
        data, frm = await sock.recv_from()
        assert data == b"hello"
        await sock.send_to(frm, b"world")

    async def b():
        sock = await UdpSocket.bind(("10.0.0.2", 5000))
        await barrier.wait()
        await sock.send_to(("10.0.0.1", 5000), b"hello")
        data, frm = await sock.recv_from()
        assert data == b"world" and frm == ("10.0.0.1", 5000)

    n1.spawn(a())
    h = n2.spawn(b())

    async def main():
        await h

    rt.block_on(main())


def test_netsim_stat_counts_messages():
    rt = ms.Runtime(seed=1)
    n1, n2 = make_two_nodes(rt)

    async def sender():
        ep = await Endpoint.bind(("10.0.0.1", 1))
        for _ in range(5):
            await ep.send_to(("10.0.0.2", 1), 1, b"x")

    async def receiver():
        ep = await Endpoint.bind(("10.0.0.2", 1))
        for _ in range(5):
            await ep.recv_from(1)

    n1.spawn(sender())
    h = n2.spawn(receiver())

    async def main():
        await h
        assert ms.simulator(NetSim).stat().msg_count >= 5

    rt.block_on(main())


def test_full_net_determinism():
    """Same seed ⇒ identical message trace through the whole stack."""

    def run(seed):
        rt = ms.Runtime(seed=seed)
        n1, n2 = make_two_nodes(rt)
        trace = []

        async def server():
            ep = await Endpoint.bind(("10.0.0.1", 1))
            for _ in range(10):
                data, frm = await ep.recv_from(1)
                trace.append((round(time.monotonic(), 9), bytes(data)))

        async def client():
            await time.sleep(0.05)
            ep = await Endpoint.bind(("10.0.0.2", 1))
            for i in range(10):
                await ep.send_to(("10.0.0.1", 1), 1, bytes([i]))
                await time.sleep(0.01)

        h = n1.spawn(server())
        n2.spawn(client())

        async def main():
            await h

        rt.block_on(main())
        return tuple(trace)

    assert run(5) == run(5)
    assert run(5) != run(6)
