"""Tests for the ecosystem shims (asyncio, gRPC, postgres)."""
import pytest

import madsim_tpu as ms
from madsim_tpu import task, time
from madsim_tpu.shims import aio, grpc_sim, postgres

# On 3.11+ this IS the builtin; on 3.10 it is the shim's stand-in that sim
# TaskGroups raise — either way the TaskGroup contract tests can catch it.
ExceptionGroup = aio.ExceptionGroup


# ---------------------------------------------------------------------------
# aio: asyncio-shaped surface
# ---------------------------------------------------------------------------

def test_aio_surface_runs_in_sim():
    async def main():
        q = aio.Queue()
        ev = aio.Event()
        results = []

        async def producer():
            for i in range(3):
                await aio.sleep(0.01)
                await q.put(i)
            ev.set()
            return "done"

        t = aio.create_task(producer())
        await ev.wait()
        while not q.empty():
            results.append(q.get_nowait())
        assert await t == "done"
        got = await aio.gather(aio.sleep(0.01, result="a"),
                               aio.sleep(0.02, result="b"))
        assert got == ["a", "b"]
        with pytest.raises(TimeoutError):
            await aio.wait_for(aio.sleep(10), timeout=0.05)
        return results

    assert ms.run(main(), seed=1) == [0, 1, 2]


def test_aio_task_exception_contained():
    async def main():
        async def boom():
            await aio.sleep(0.01)
            raise ValueError("boom")

        t = aio.create_task(boom())
        with pytest.raises(ValueError):
            await t
        assert isinstance(t.exception(), ValueError)
        # gather with return_exceptions
        got = await aio.gather(boom(), aio.sleep(0, result=1),
                               return_exceptions=True)
        assert isinstance(got[0], ValueError) and got[1] == 1
        return "survived"

    assert ms.run(main(), seed=2) == "survived"


def test_aio_task_cancel():
    async def main():
        hits = []

        async def worker():
            while True:
                await aio.sleep(0.01)
                hits.append(1)

        t = aio.create_task(worker())
        await aio.sleep(0.055)
        assert t.cancel()
        # asyncio semantics: cancel() REQUESTS; completion is observed by
        # awaiting (CancelledError is delivered inside the task).
        with pytest.raises(aio.CancelledError):
            await t
        assert t.done() and t.cancelled()
        n = len(hits)
        await aio.sleep(0.05)
        assert len(hits) == n  # really stopped
        return True

    assert ms.run(main(), seed=3)


def test_aio_task_can_catch_cancellation_for_cleanup():
    async def main():
        cleaned = []

        async def worker():
            try:
                await aio.sleep(100.0)
            except aio.CancelledError:
                cleaned.append(True)   # asyncio cleanup idiom
                raise

        t = aio.create_task(worker())
        await aio.sleep(0.01)
        t.cancel()
        with pytest.raises(aio.CancelledError):
            await t
        assert cleaned == [True]
        return True

    assert ms.run(main(), seed=4, time_limit=30)


# ---------------------------------------------------------------------------
# aio: interpreter-level patching (the libc-interception analog)
# ---------------------------------------------------------------------------

def unmodified_asyncio_app():
    """Written purely against stdlib asyncio/random/time."""
    import asyncio
    import random
    import time as wall

    async def app():
        t0 = wall.monotonic()
        out = []

        async def worker(i):
            await asyncio.sleep(random.uniform(0.01, 0.05))
            out.append((i, round(wall.monotonic() - t0, 6), wall.time()))

        tasks = [asyncio.create_task(worker(i)) for i in range(4)]
        await asyncio.gather(*tasks)
        return out

    return app()


def test_patched_runs_unmodified_asyncio_code_deterministically():
    with aio.patched():
        a = ms.run(unmodified_asyncio_app(), seed=7)
        b = ms.run(unmodified_asyncio_app(), seed=7)
        c = ms.run(unmodified_asyncio_app(), seed=8)
    assert a == b            # same seed ⇒ bit-identical schedule & clocks
    assert a != c            # different seed ⇒ different world
    # virtual wall-clock base is the seed-randomized 2022 range
    years = {int(row[2] // (365.25 * 24 * 3600)) + 1970 for row in a}
    assert years <= {2022, 2023}


def test_patched_to_thread_is_deterministic_in_sim():
    # asyncio.to_thread inside a patched sim must run as a deterministic
    # task (real threads would reintroduce scheduling nondeterminism) and
    # still be real threads outside.
    async def main():
        import asyncio
        import time as walltime

        def work(x):
            return (x * 2, walltime.monotonic())

        pairs = await asyncio.gather(asyncio.to_thread(work, 1),
                                     asyncio.to_thread(work, 2))
        return pairs

    with aio.patched():
        a = ms.run(main(), seed=9)
        b = ms.run(main(), seed=9)
    assert a == b  # identical results AND identical virtual timestamps
    assert [v for v, _t in a] == [2, 4]

    import asyncio as real_asyncio
    with aio.patched():
        out = real_asyncio.run(main())  # outside sim: passthrough
    assert [v for v, _t in out] == [2, 4]


def test_patched_randrange_respects_step():
    async def main():
        import random

        return [random.randrange(0, 100, 5) for _ in range(32)]

    with aio.patched():
        vals = ms.run(main(), seed=13)
    assert all(v % 5 == 0 and 0 <= v < 100 for v in vals)
    assert len(set(vals)) > 3


def test_patched_queue_empty_is_asyncio_exception():
    async def main():
        import asyncio

        q = asyncio.Queue()
        try:
            q.get_nowait()
        except asyncio.QueueEmpty:
            return "caught"

    with aio.patched():
        assert ms.run(main(), seed=14) == "caught"


def test_patched_falls_through_outside_sim():
    import random
    import time as wall

    with aio.patched():
        # Outside a simulation the patched functions hit the real impls.
        assert wall.time() > 1.5e9
        v = random.random()
        assert 0.0 <= v < 1.0
    # After uninstall the originals are restored.
    assert wall.time.__module__ == "time" or callable(wall.time)


def test_patched_cpu_introspection_sees_node_cores():
    # The sched_getaffinity/sysconf interception analog (`task.rs:508-560`,
    # VERDICT "What's missing" #2): unmodified third-party code sizing a
    # thread pool inside a sim node must observe the NODE's configured
    # cores, matching task.available_parallelism() — not the host machine.
    import os as real_os

    host_cpus = real_os.cpu_count()
    rt = ms.Runtime(seed=5)
    node = rt.create_node(name="big", cores=6)
    out = {}

    async def probe():
        import os
        from concurrent.futures import ThreadPoolExecutor

        out["cpu_count"] = os.cpu_count()
        if hasattr(os, "process_cpu_count"):
            out["process_cpu_count"] = os.process_cpu_count()
        out["affinity"] = os.sched_getaffinity(0)
        # Default-sized executor: stdlib computes max_workers from the
        # (patched) cpu count at construction time; no thread starts until
        # submit, so building one in-sim is safe.
        pool = ThreadPoolExecutor()
        out["pool_workers"] = pool._max_workers
        pool.shutdown(wait=False)

    async def main():
        await node.spawn(probe())

    with aio.patched():
        rt.block_on(main())
        # Outside the sim the passthrough still reports the host.
        import os

        assert os.cpu_count() == host_cpus
    assert out["cpu_count"] == 6
    assert out.get("process_cpu_count", 6) == 6
    assert out["affinity"] == set(range(6))
    assert out["pool_workers"] == min(32, 6 + 4)


# ---------------------------------------------------------------------------
# gRPC shim
# ---------------------------------------------------------------------------

class Greeter:
    SERVICE_NAME = "helloworld.Greeter"

    @grpc_sim.unary
    async def SayHello(self, request, context):
        if request == "error":
            raise grpc_sim.Status(grpc_sim.StatusCode.INVALID_ARGUMENT, "bad name")
        return f"Hello {request}! ({context.peer().split(':')[0]})"

    @grpc_sim.server_streaming
    async def LotsOfReplies(self, request, context):
        for i in range(3):
            await time.sleep(0.01)
            yield f"{request}-{i}"

    @grpc_sim.client_streaming
    async def LotsOfGreetings(self, requests, context):
        names = [r async for r in requests]
        return f"Hello {', '.join(names)}!"

    @grpc_sim.bidi
    async def BidiHello(self, requests, context):
        async for r in requests:
            yield f"echo:{r}"


def _grpc_world(client_body):
    async def main():
        h = ms.Handle.current()
        server = grpc_sim.Server().add_service(Greeter())

        async def serve():
            await server.serve(("10.0.0.1", 50051))

        h.create_node(name="server", ip="10.0.0.1", init=serve)
        result = ms.sync.SimFuture()

        async def client():
            ch = await grpc_sim.Channel.connect(("10.0.0.1", 50051))
            try:
                result.set_result(await client_body(ch))
            except BaseException as exc:  # noqa: BLE001
                result.set_exception(exc)

        h.create_node(name="client", ip="10.0.0.2", init=client)
        return await time.timeout(30, _await(result))

    return ms.run(main(), seed=11)


async def _await(fut):
    return await fut


def test_grpc_unary():
    async def body(ch):
        return await ch.unary("/helloworld.Greeter/SayHello", "world")

    assert _grpc_world(body) == "Hello world! (10.0.0.2)"


def test_grpc_unary_error_status():
    async def body(ch):
        with pytest.raises(grpc_sim.Status) as ei:
            await ch.unary("/helloworld.Greeter/SayHello", "error")
        return ei.value.code

    assert _grpc_world(body) == grpc_sim.StatusCode.INVALID_ARGUMENT


def test_grpc_unknown_path():
    async def body(ch):
        with pytest.raises(grpc_sim.Status) as ei:
            await ch.unary("/helloworld.Greeter/Nope", "x")
        return ei.value.code

    assert _grpc_world(body) == grpc_sim.StatusCode.UNIMPLEMENTED


def test_grpc_server_streaming():
    async def body(ch):
        return [r async for r in
                ch.server_streaming("/helloworld.Greeter/LotsOfReplies", "s")]

    assert _grpc_world(body) == ["s-0", "s-1", "s-2"]


def test_grpc_client_streaming():
    async def body(ch):
        async def names():
            for n in ["alice", "bob"]:
                await time.sleep(0.01)
                yield n

        return await ch.client_streaming("/helloworld.Greeter/LotsOfGreetings",
                                         names())

    assert _grpc_world(body) == "Hello alice, bob!"


def test_grpc_bidi():
    async def body(ch):
        async def reqs():
            for n in range(3):
                yield n

        return [r async for r in ch.bidi("/helloworld.Greeter/BidiHello", reqs())]

    assert _grpc_world(body) == ["echo:0", "echo:1", "echo:2"]


def test_grpc_end_sentinel_payload_not_truncating():
    # A user payload equal to the internal ("end", None) terminator must
    # cross the stream intact (requests are framed, not sent raw).
    async def body(ch):
        async def reqs():
            yield ("end", None)
            yield "after"

        return [r async for r in ch.bidi("/helloworld.Greeter/BidiHello", reqs())]

    assert _grpc_world(body) == ["echo:('end', None)", "echo:after"]


def test_grpc_connection_refused():
    async def body(ch):
        with pytest.raises(grpc_sim.Status) as ei:
            await ch.unary("/x/y", "z")
        return ei.value.code

    async def main():
        h = ms.Handle.current()
        result = ms.sync.SimFuture()

        async def client():
            ch = grpc_sim.Channel(await __import__("madsim_tpu").net.Endpoint.bind("0.0.0.0:0"),
                                  ("10.9.9.9", 1))
            try:
                result.set_result(await body(ch))
            except BaseException as exc:  # noqa: BLE001
                result.set_exception(exc)

        h.create_node(name="client", ip="10.0.0.2", init=client)
        return await time.timeout(30, _await(result))

    assert ms.run(main(), seed=12) == grpc_sim.StatusCode.UNAVAILABLE


@ms.test(seed=1, count=5, time_limit=300)
async def test_grpc_survives_server_restart():
    """tonic-example client_crash analog: restart the *server* under load."""
    h = ms.Handle.current()

    async def serve():
        # A fresh Server per incarnation (the old one died with the node).
        srv = grpc_sim.Server().add_service(Greeter())
        await srv.serve(("10.0.0.1", 50051))

    server_node = h.create_node(name="server", ip="10.0.0.1", init=serve)
    progress = []

    async def client():
        ch = await grpc_sim.Channel.connect(("10.0.0.1", 50051))
        while True:
            try:
                rsp = await time.timeout(
                    1.0, ch.unary("/helloworld.Greeter/SayHello", "chaos"))
                progress.append(rsp)
            except (grpc_sim.Status, TimeoutError):
                await time.sleep(0.05)

    h.create_node(name="client", ip="10.0.0.2", init=client)

    for _ in range(3):
        await time.sleep(ms.rand.thread_rng().gen_range_f64(0.5, 1.5))
        h.restart(server_node)
    await time.sleep(2.0)
    assert len(progress) > 5  # made progress across restarts


# ---------------------------------------------------------------------------
# postgres shim
# ---------------------------------------------------------------------------

def _pg_world(client_body, seed=21):
    async def main():
        h = ms.Handle.current()

        async def serve():
            await postgres.SimPostgresServer().serve(("10.0.0.1", 5432))

        h.create_node(name="db", ip="10.0.0.1", init=serve)
        result = ms.sync.SimFuture()

        async def client():
            await time.sleep(0.1)  # let the server bind
            conn = await postgres.connect("10.0.0.1", 5432, user="app")
            try:
                result.set_result(await client_body(conn))
            except BaseException as exc:  # noqa: BLE001
                result.set_exception(exc)
            finally:
                await conn.close()

        h.create_node(name="app", ip="10.0.0.2", init=client)
        return await time.timeout(60, _await(result))

    return ms.run(main(), seed=seed)


def test_postgres_roundtrip():
    async def body(conn):
        assert conn.parameters["server_version"] == "15.0-sim"
        await conn.execute("CREATE TABLE users (id, name)")
        await conn.execute("INSERT INTO users VALUES ('1', 'ada')")
        await conn.execute("INSERT INTO users VALUES ('2', 'grace')")
        rows = await conn.query("SELECT * FROM users")
        assert [tuple(r) for r in rows] == [("1", "ada"), ("2", "grace")]
        rows = await conn.query("SELECT name FROM users WHERE id = '2'")
        assert rows[0].get("name") == "grace"
        await conn.execute("DELETE FROM users WHERE id = '1'")
        rows = await conn.query("SELECT * FROM users")
        return [tuple(r) for r in rows]

    assert _pg_world(body) == [("2", "grace")]


def test_postgres_errors():
    async def body(conn):
        with pytest.raises(postgres.PostgresError) as ei:
            await conn.query("SELECT * FROM nope")
        assert ei.value.code == "42P01"
        with pytest.raises(postgres.PostgresError):
            await conn.query("THIS IS NOT SQL")
        # the connection stays usable after errors (ReadyForQuery resync)
        await conn.execute("CREATE TABLE t (a)")
        await conn.execute("INSERT INTO t VALUES ('x')")
        return len(await conn.query("SELECT * FROM t"))

    assert _pg_world(body) == 1


def test_postgres_deterministic_same_seed():
    async def body(conn):
        await conn.execute("CREATE TABLE t (a)")
        for i in range(5):
            await conn.execute(f"INSERT INTO t VALUES ('{i}')")
        rows = await conn.query("SELECT * FROM t")
        return (len(rows), time.monotonic())

    a = _pg_world(body, seed=33)
    b = _pg_world(body, seed=33)
    c = _pg_world(body, seed=34)
    assert a == b
    assert a != c  # different schedule/latency draws


def test_postgres_prepared_statements():
    # Extended-query protocol: Parse/Describe/Bind/Execute/Close/Sync
    # (prepare.rs / codec.rs analog).
    async def body(conn):
        await conn.execute("CREATE TABLE kv (k, v)")
        ins = await conn.prepare("INSERT INTO kv VALUES ($1, $2)")
        assert ins.n_params == 2 and ins.columns == []
        sel = await conn.prepare("SELECT v FROM kv WHERE k = $1")
        assert sel.n_params == 1 and sel.columns == ["v"]
        for i in range(5):
            await conn.execute_prepared(ins, [f"k{i}", f"v{i}"])
        got = []
        for i in range(5):
            rows = await conn.query_prepared(sel, [f"k{i}"])
            got.append(rows[0].get("v"))
        # NULL parameter round-trip + quote escaping through Bind.
        await conn.execute_prepared(ins, ["quote", "it's"])
        rows = await conn.query_prepared(sel, ["quote"])
        assert rows[0][0] == "it's"
        await conn.close_statement(ins)
        with pytest.raises(postgres.PostgresError) as ei:
            await conn.query_prepared(ins, ["x", "y"])  # closed statement
        assert ei.value.code == "26000"
        # Connection resyncs after the extended-flow error.
        return got + [(await conn.query_prepared(sel, ["k0"]))[0][0]]

    assert _pg_world(body) == [f"v{i}" for i in range(5)] + ["v0"]


def test_postgres_transactions():
    async def body(conn):
        await conn.execute("CREATE TABLE t (a)")
        # Commit path.
        async with conn.transaction():
            await conn.execute("INSERT INTO t VALUES ('committed')")
            assert conn.txn_status == "T"
        assert conn.txn_status == "I"
        # Rollback path (exception unwinds the block).
        with pytest.raises(RuntimeError):
            async with conn.transaction():
                await conn.execute("INSERT INTO t VALUES ('doomed')")
                raise RuntimeError("app failure")
        rows = await conn.query("SELECT * FROM t")
        assert [r[0] for r in rows] == ["committed"]
        # A failed statement poisons the transaction: 25P02 until ROLLBACK,
        # and COMMIT of a failed transaction rolls back.
        await conn.execute("BEGIN")
        await conn.execute("INSERT INTO t VALUES ('poisoned')")
        with pytest.raises(postgres.PostgresError):
            await conn.query("SELECT * FROM nope")
        assert conn.txn_status == "E"
        with pytest.raises(postgres.PostgresError) as ei:
            await conn.query("SELECT * FROM t")
        assert ei.value.code == "25P02"
        await conn.execute("COMMIT")  # acts as ROLLBACK
        rows = await conn.query("SELECT * FROM t")
        return [r[0] for r in rows]

    assert _pg_world(body) == ["committed"]


def test_postgres_rollback_preserves_concurrent_commits():
    # Undo-log semantics: session A's ROLLBACK must not erase rows that
    # session B committed while A's transaction was open.
    async def main():
        h = ms.Handle.current()
        server = postgres.SimPostgresServer()

        async def serve():
            await server.serve(("10.0.0.1", 5432))

        h.create_node(name="db", ip="10.0.0.1", init=serve)
        done = ms.sync.SimFuture()

        async def app():
            await time.sleep(0.1)
            a = await postgres.connect("10.0.0.1")
            b = await postgres.connect("10.0.0.1")
            await a.execute("CREATE TABLE t (k)")
            await a.execute("BEGIN")
            await a.execute("INSERT INTO t VALUES ('from_a')")
            # B commits mid-A-transaction.
            await b.execute("INSERT INTO t VALUES ('from_b')")
            await a.execute("ROLLBACK")
            rows = await a.query("SELECT * FROM t")
            await a.close()
            await b.close()
            done.set_result(sorted(r[0] for r in rows))

        h.create_node(name="app", ip="10.0.0.2", init=app)
        return await time.timeout(60, _await(done))

    assert ms.run(main(), seed=9) == ["from_b"]


def test_postgres_values_with_commas_and_quotes():
    async def body(conn):
        await conn.execute("CREATE TABLE t (k, v)")
        ins = await conn.prepare("INSERT INTO t VALUES ($1, $2)")
        sel = await conn.prepare("SELECT v FROM t WHERE k = $1")
        await conn.execute_prepared(ins, ["a,b", "x'y,z"])
        rows = await conn.query_prepared(sel, ["a,b"])
        assert rows[0][0] == "x'y,z"
        # `col = NULL` matches nothing (three-valued logic).
        await conn.execute_prepared(ins, [None, "nullkey"])
        assert await conn.query_prepared(sel, [None]) == []
        return True

    assert _pg_world(body)


def test_postgres_disconnect_rolls_back_open_transaction():
    # Uncommitted writes must not outlive their connection.
    async def main():
        h = ms.Handle.current()
        server = postgres.SimPostgresServer()

        async def serve():
            await server.serve(("10.0.0.1", 5432))

        h.create_node(name="db", ip="10.0.0.1", init=serve)
        done = ms.sync.SimFuture()

        async def app():
            await time.sleep(0.1)
            a = await postgres.connect("10.0.0.1")
            await a.execute("CREATE TABLE t (k)")
            await a.execute("BEGIN")
            await a.execute("INSERT INTO t VALUES ('uncommitted')")
            await a.close()  # Terminate with the transaction still open
            b = await postgres.connect("10.0.0.1")
            rows = await b.query("SELECT * FROM t")
            await b.close()
            done.set_result([r[0] for r in rows])

        h.create_node(name="app", ip="10.0.0.2", init=app)
        return await time.timeout(60, _await(done))

    assert ms.run(main(), seed=12) == []


def test_postgres_bad_placeholder_and_pending_ddl():
    async def main():
        h = ms.Handle.current()
        server = postgres.SimPostgresServer()

        async def serve():
            await server.serve(("10.0.0.1", 5432))

        h.create_node(name="db", ip="10.0.0.1", init=serve)
        done = ms.sync.SimFuture()

        async def app():
            await time.sleep(0.1)
            a = await postgres.connect("10.0.0.1")
            # $0 is not a parameter: the server must error, not crash.
            s = await a.prepare("SELECT k FROM t WHERE k = $0")
            with pytest.raises(postgres.PostgresError) as ei:
                await a.query_prepared(s, [])
            assert ei.value.code == "42P02"
            # DDL inside an open transaction is invisible to other sessions
            # until commit; rollback drops it without touching anyone else.
            b = await postgres.connect("10.0.0.1")
            await a.execute("BEGIN")
            await a.execute("CREATE TABLE pend (k)")
            with pytest.raises(postgres.PostgresError) as ei:
                await b.query("SELECT * FROM pend")
            assert ei.value.code == "42P01"
            await a.execute("ROLLBACK")
            with pytest.raises(postgres.PostgresError):
                await a.query("SELECT * FROM pend")  # dropped by rollback
            # Committed DDL becomes visible.
            await a.execute("BEGIN")
            await a.execute("CREATE TABLE pub (k)")
            await a.execute("COMMIT")
            assert await b.query("SELECT * FROM pub") == []
            await a.close()
            await b.close()
            done.set_result(True)

        h.create_node(name="app", ip="10.0.0.2", init=app)
        return await time.timeout(60, _await(done))

    assert ms.run(main(), seed=13)


def test_postgres_copy_roundtrip():
    async def body(conn):
        await conn.execute("CREATE TABLE t (id, name, note)")
        sink = await conn.copy_in("COPY t FROM STDIN")
        await sink.write_row(["1", "ada", None])
        # Escaping: tabs/newlines/backslashes in data survive the text codec.
        await sink.write_row(["2", "gr\tace", "a\\b\nc"])
        n = await sink.finish()
        assert n == 2
        rows = await conn.copy_out("COPY t TO STDOUT")
        assert rows == [["1", "ada", None], ["2", "gr\tace", "a\\b\nc"]]
        # Column-list COPY: unlisted columns fill with NULL; COPY TO with a
        # column list projects.
        sink = await conn.copy_in("COPY t (name) FROM STDIN")
        await sink.write_row(["hopper"])
        assert await sink.finish() == 1
        names = await conn.copy_out("COPY t (name) TO STDOUT")
        assert [r[0] for r in names] == ["ada", "gr\tace", "hopper"]
        full = await conn.query("SELECT * FROM t WHERE name = 'hopper'")
        return [tuple(r) for r in full]

    assert _pg_world(body) == [(None, "hopper", None)]


def test_postgres_copy_codec_edge_cases():
    # An empty-string single-column row is a bare newline on the wire —
    # it must round-trip, not vanish.
    assert postgres.copy_decode(postgres.copy_encode_row([""])) == [[""]]
    # The \. end-of-data marker terminates the stream (psql semantics):
    # nothing after it is a row.
    assert postgres.copy_decode(b"a\n\\.\nb\n") == [["a"]]

    async def body(conn):
        await conn.execute("CREATE TABLE t (k)")
        sink = await conn.copy_in("COPY t FROM STDIN")
        await sink.write_row([""])
        await sink.write(b"x\n\\.\nignored\n")
        n = await sink.finish()
        # Writing after finish is rejected locally, keeping the wire clean.
        with pytest.raises(postgres.PostgresError):
            await sink.write_row(["late"])
        rows = await conn.copy_out("COPY t TO STDOUT")
        return n, rows

    assert _pg_world(body) == (2, [[""], ["x"]])


def test_postgres_copy_transactional_and_failures():
    async def body(conn):
        await conn.execute("CREATE TABLE t (k)")
        # COPY FROM inside a transaction rolls back with it.
        await conn.execute("BEGIN")
        sink = await conn.copy_in("COPY t FROM STDIN")
        await sink.write_row(["lost"])
        assert await sink.finish() == 1
        await conn.execute("ROLLBACK")
        assert await conn.copy_out("COPY t TO STDOUT") == []
        # CopyFail discards the data and reports 57014 without poisoning
        # a fresh session state.
        sink = await conn.copy_in("COPY t FROM STDIN")
        await sink.write_row(["discarded"])
        await sink.fail("client changed its mind")
        assert await conn.query("SELECT * FROM t") == []
        # Unknown table: no COPY mode is entered, the error surfaces.
        with pytest.raises(postgres.PostgresError) as ei:
            await conn.copy_in("COPY nope FROM STDIN")
        assert ei.value.code == "42P01"
        with pytest.raises(postgres.PostgresError) as ei:
            await conn.copy_out("COPY nope TO STDOUT")
        assert ei.value.code == "42P01"
        # Wrong column count in the stream: 22P04 at finish.
        sink = await conn.copy_in("COPY t FROM STDIN")
        await sink.write(b"a\tb\n")
        with pytest.raises(postgres.PostgresError) as ei:
            await sink.finish()
        assert ei.value.code == "22P04"
        # An in-transaction COPY error poisons the transaction (25P02).
        await conn.execute("BEGIN")
        sink = await conn.copy_in("COPY t FROM STDIN")
        await sink.write(b"x\ty\n")
        with pytest.raises(postgres.PostgresError):
            await sink.finish()
        with pytest.raises(postgres.PostgresError) as ei:
            await conn.query("SELECT * FROM t")
        assert ei.value.code == "25P02"
        await conn.execute("ROLLBACK")
        return await conn.query("SELECT * FROM t")

    assert _pg_world(body) == []


def test_postgres_copy_unexpected_message_drains_stream():
    # Regression (round-4 advice): an unexpected message mid-COPY must make
    # the server drain the rest of the copy stream (to CopyDone/CopyFail)
    # before reporting one error; the trailing CopyData frames must not
    # desync the request/response cycle (real-postgres behavior).
    async def body(conn):
        await conn.execute("CREATE TABLE t (k)")
        await conn.copy_in("COPY t FROM STDIN")
        raw = (postgres._msg(b"d", b"1\n")
               + postgres._msg(b"?", b"")       # unexpected mid-COPY
               + postgres._msg(b"d", b"2\n")    # client still mid-stream
               + postgres._msg(b"c", b""))
        await conn._stream.write_all(raw)
        with pytest.raises(postgres.PostgresError) as ei:
            await conn._read_until_ready()
        assert ei.value.code == "08P01"
        # Exactly one error + ReadyForQuery: the session is back in sync
        # and the partial copy was discarded.
        return await conn.query("SELECT * FROM t")

    assert _pg_world(body) == []


def test_postgres_copy_out_invalid_utf8_is_postgres_error():
    # Regression (round-4 advice): non-UTF-8 CopyData from the server must
    # surface as PostgresError 22P04, not a raw UnicodeDecodeError.
    import struct

    from madsim_tpu.net.tcp import TcpListener

    async def main():
        h = ms.Handle.current()

        async def rogue_server():
            listener = await TcpListener.bind(("10.0.0.1", 5432))
            stream, _ = await listener.accept()
            head = await stream.read_exact(8)
            (length, _ver) = struct.unpack("!II", head)
            if length > 8:
                await stream.read_exact(length - 8)
            await stream.write_all(
                postgres._msg(b"R", b"\0\0\0\0")
                + postgres._msg(b"Z", b"I"))
            mtype, _ = await postgres._read_message(stream)
            assert mtype == b"Q"
            await stream.write_all(
                postgres._msg(b"H", b"\0\0\0")
                + postgres._msg(b"d", b"\xff\xfe\n")   # invalid UTF-8
                + postgres._msg(b"c", b"")
                + postgres._msg(b"C", b"COPY 1\0")
                + postgres._msg(b"Z", b"I"))

        h.create_node(name="db", ip="10.0.0.1", init=rogue_server)
        result = ms.sync.SimFuture()

        async def client():
            await time.sleep(0.1)
            conn = await postgres.connect("10.0.0.1")
            try:
                await conn.copy_out("COPY t TO STDOUT")
                result.set_result("no error")
            except postgres.PostgresError as exc:
                result.set_result(exc.code)

        h.create_node(name="app", ip="10.0.0.2", init=client)
        return await time.timeout(60, _await(result))

    assert ms.run(main(), seed=7) == "22P04"


def test_postgres_prepared_txn_under_loss_and_restart():
    # The VERDICT bar: prepared statements + transaction rollback while the
    # network drops packets and the DB node restarts mid-run.
    def world(seed):
        cfg = ms.Config()
        cfg.net.packet_loss_rate = 0.05

        async def main():
            h = ms.Handle.current()
            server = postgres.SimPostgresServer()

            async def serve():
                await server.serve(("10.0.0.1", 5432))

            db = h.create_node(name="db", ip="10.0.0.1", init=serve)
            done = ms.sync.SimFuture()

            async def client():
                committed = []
                for batch in range(6):
                    while True:  # reconnect loop across restarts
                        try:
                            conn = await postgres.connect("10.0.0.1", 5432)
                            try:
                                rows = await conn.query(
                                    "SELECT * FROM bank WHERE k = 'seed'")
                            except postgres.PostgresError:
                                await conn.execute("CREATE TABLE bank (k, v)")
                            ins = await conn.prepare(
                                "INSERT INTO bank VALUES ($1, $2)")
                            async with conn.transaction():
                                await conn.execute_prepared(
                                    ins, [f"b{batch}", "1"])
                                await conn.execute_prepared(
                                    ins, [f"b{batch}", "2"])
                            committed.append(batch)
                            await conn.close()
                            break
                        except (OSError, postgres.PostgresError,
                                TimeoutError):
                            await time.sleep(0.2)
                done.set_result(committed)

            h.create_node(name="app", ip="10.0.0.2", init=client)
            await time.sleep(1.0)
            h.restart(db)  # server loses volatile tables; client reconnects
            return await time.timeout(300, _await(done))

        rt = ms.Runtime(seed=seed, config=cfg)
        return rt.block_on(main())

    a = world(3)
    b = world(3)
    assert a == b, "chaos run must be seed-deterministic"
    assert len(a) == 6


# ---------------------------------------------------------------------------
# Modern asyncio surface (3.11+): TaskGroup / timeout / wait / as_completed /
# Condition — what current pip libraries are written against.
# ---------------------------------------------------------------------------

def test_aio_taskgroup_and_timeout_scope():
    async def main():
        order = []
        async with aio.TaskGroup() as tg:
            async def worker(i, d):
                await aio.sleep(d)
                order.append(i)

            for i, d in enumerate([0.03, 0.01, 0.02]):
                tg.create_task(worker(i, d))
        assert order == [1, 2, 0]  # completion order = virtual-time order

        # asyncio.timeout must interrupt a hung await mid-flight.
        t0 = time.monotonic()
        try:
            async with aio.timeout(0.05):
                await ms.sync.SimFuture()  # never resolves
            raise AssertionError("expected TimeoutError")
        except TimeoutError:
            pass
        assert 0.04 < time.monotonic() - t0 < 0.2

        # A body that finishes in time passes through untouched.
        async with aio.timeout(10.0) as scope:
            await aio.sleep(0.01)
        assert not scope.expired()
        return True

    assert ms.run(main(), seed=5)


def test_aio_taskgroup_failure_cancels_siblings():
    async def main():
        try:
            async with aio.TaskGroup() as tg:
                async def doomed():
                    await aio.sleep(0.01)
                    raise ValueError("boom")

                async def hung_sibling():
                    await ms.sync.SimFuture()  # never resolves

                # The hung sibling is created FIRST: its failure to finish
                # must not mask the later child's error (asyncio reacts to
                # failures as they happen, not in creation order).
                tg.create_task(hung_sibling())
                tg.create_task(doomed())
            raise AssertionError("expected ExceptionGroup")
        except ExceptionGroup as eg:  # the real asyncio.TaskGroup contract
            assert len(eg.exceptions) == 1
            assert isinstance(eg.exceptions[0], ValueError)
        return True

    assert ms.run(main(), seed=6, time_limit=30)


def test_aio_taskgroup_body_exception_cancels_children():
    async def main():
        try:
            async with aio.TaskGroup() as tg:
                async def server_loop():
                    await ms.sync.SimFuture()  # runs forever

                tg.create_task(server_loop())
                raise ValueError("body failed")
        except ValueError:
            pass  # the body's exception, not a hang until time_limit
        return True

    assert ms.run(main(), seed=16, time_limit=30)


def test_aio_timeout_does_not_poison_shared_futures():
    # Cancelling a timed-out wait must interrupt the WAITER only: the
    # awaited task keeps running and its result stays intact for others.
    async def main():
        async def slow():
            await aio.sleep(0.2)
            return "value"

        t = aio.create_task(slow())
        try:
            async with aio.timeout(0.05):
                await t
            raise AssertionError("expected TimeoutError")
        except TimeoutError:
            pass
        assert not t.done()          # still running, not poisoned
        assert await t == "value"    # other waiters see the real result
        return True

    assert ms.run(main(), seed=17, time_limit=30)


def test_aio_wait_and_as_completed():
    async def main():
        async def v(i, d):
            await aio.sleep(d)
            return i

        done, pending = await aio.wait(
            [v(0, 0.05), v(1, 0.01)], return_when=aio.FIRST_COMPLETED)
        assert {t.result() for t in done} == {1}
        assert len(pending) == 1
        done2, pending2 = await aio.wait(pending)
        assert not pending2 and {t.result() for t in done2} == {0}

        got = []
        for nxt in aio.as_completed([v(10, 0.03), v(11, 0.01), v(12, 0.02)]):
            got.append(await nxt)  # resolves to the RESULT (asyncio contract)
        assert got == [11, 12, 10]

        # A child exception surfaces at the await point, and the timeout is
        # one overall deadline across the iteration.
        async def bad():
            await aio.sleep(0.01)
            raise RuntimeError("child failed")

        it = aio.as_completed([bad()], timeout=10.0)
        with pytest.raises(RuntimeError):
            await next(iter(it))
        t0 = time.monotonic()
        try:
            for nxt in aio.as_completed(
                    [v(0, 0.02), v(1, 5.0), v(2, 5.0)], timeout=0.1):
                await nxt
            raise AssertionError("expected TimeoutError")
        except TimeoutError:
            pass
        assert time.monotonic() - t0 < 0.2  # one deadline, not per-item
        return True

    assert ms.run(main(), seed=7)


def test_aio_condition():
    async def main():
        cond = aio.Condition()
        items = []
        got = []

        async def consumer():
            async with cond:
                while len(got) < 3:
                    await cond.wait_for(lambda: bool(items))
                    got.append(items.pop(0))

        async def producer():
            for i in range(3):
                await aio.sleep(0.01)
                async with cond:
                    items.append(i)
                    cond.notify()

        async with aio.TaskGroup() as tg:
            tg.create_task(consumer())
            tg.create_task(producer())
        assert got == [0, 1, 2]
        return True

    assert ms.run(main(), seed=8, time_limit=30)


def test_aio_patched_covers_modern_names():
    import asyncio as real_asyncio

    async def main():
        with aio.patched():
            async with real_asyncio.timeout(1.0):
                await real_asyncio.sleep(0.01)
            async with real_asyncio.TaskGroup() as tg:
                t = tg.create_task(real_asyncio.sleep(0.01, result="x"))
            assert t.result() == "x"
            done, _ = await real_asyncio.wait(
                [real_asyncio.sleep(0.01, result="y")])
            assert {x.result() for x in done} == {"y"}
        return True

    assert ms.run(main(), seed=9)


def test_aio_timeout_does_not_leak_locks_or_notifications():
    # A waiter cancelled by a timeout scope must not corrupt the primitive.
    async def main():
        lock = aio.Lock()
        await lock.acquire()

        async def blocked_acquirer():
            try:
                async with aio.timeout(0.02):
                    await lock.acquire()
            except TimeoutError:
                return "timed_out"

        t = aio.create_task(blocked_acquirer())
        await aio.sleep(0.05)
        assert await t == "timed_out"
        lock.release()
        await lock.acquire()   # must not deadlock: no leaked handoff
        lock.release()

        # Condition: a dead waiter must not eat a notification.
        cond = aio.Condition()
        got = []

        async def dead_waiter():
            try:
                async with aio.timeout(0.02):
                    async with cond:
                        await cond.wait()
            except TimeoutError:
                pass

        async def live_waiter():
            async with cond:
                await cond.wait()
                got.append("woken")

        aio.create_task(dead_waiter())
        t2 = aio.create_task(live_waiter())
        await aio.sleep(0.05)   # dead waiter has timed out by now
        async with cond:
            cond.notify(1)      # must reach the LIVE waiter
        await t2
        assert got == ["woken"]
        return True

    assert ms.run(main(), seed=18, time_limit=30)


def test_aio_taskgroup_tracks_children_spawned_by_children():
    async def main():
        order = []

        async with aio.TaskGroup() as tg:
            async def grandchild():
                await aio.sleep(0.02)
                order.append("grandchild")

            async def child():
                order.append("child")
                tg.create_task(grandchild())  # standard asyncio pattern

            tg.create_task(child())
        # The group must not exit until the late grandchild finished.
        assert order == ["child", "grandchild"]

        # A late child's failure still surfaces.
        try:
            async with aio.TaskGroup() as tg:
                async def bad_grandchild():
                    raise RuntimeError("late failure")

                async def spawner():
                    await aio.sleep(0.01)
                    tg.create_task(bad_grandchild())

                tg.create_task(spawner())
            raise AssertionError("expected ExceptionGroup")
        except ExceptionGroup as eg:
            assert isinstance(eg.exceptions[0], RuntimeError)
        return True

    assert ms.run(main(), seed=19, time_limit=30)


def test_aio_taskgroup_child_failure_tears_down_body():
    # The asyncio contract: a child failure cancels the PARENT's body too,
    # so `await serve_forever()` in the block does not hang the group.
    async def main():
        reached_after = []
        try:
            async with aio.TaskGroup() as tg:
                async def failing_child():
                    await aio.sleep(0.01)
                    raise AssertionError("child invariant")

                tg.create_task(failing_child())
                await ms.sync.SimFuture()  # serve-forever: must be torn down
                reached_after.append(True)
        except ExceptionGroup as eg:
            assert {type(e) for e in eg.exceptions} == {AssertionError}
        assert not reached_after
        return True

    assert ms.run(main(), seed=20, time_limit=30)


def test_aio_taskgroup_combines_body_and_child_errors():
    # Body fails first; a child that errors during the resulting abort
    # must still surface alongside the body's exception.
    async def main():
        try:
            async with aio.TaskGroup() as tg:
                async def protests_cancellation():
                    try:
                        await aio.sleep(100.0)
                    except aio.CancelledError:
                        raise RuntimeError("cleanup failed") from None

                tg.create_task(protests_cancellation())
                await aio.sleep(0.01)
                raise ValueError("body failed")
        except ExceptionGroup as eg:
            assert {type(e) for e in eg.exceptions} == {RuntimeError, ValueError}
            return True
        raise AssertionError("expected ExceptionGroup with both errors")

    assert ms.run(main(), seed=21, time_limit=30)


def test_aio_taskgroup_refuses_new_children_after_exit():
    async def main():
        async with aio.TaskGroup() as tg:
            tg.create_task(aio.sleep(0.01))
        with pytest.raises(RuntimeError, match="finished"):
            tg.create_task(aio.sleep(0.01))
        return True

    assert ms.run(main(), seed=22)


def test_aio_taskgroup_external_cancel_wins():
    # Cancelling the task hosting a group cancels the children and the
    # cancellation propagates (not swallowed, not orphaning children).
    async def main():
        child_cancelled = []

        async def host():
            async with aio.TaskGroup() as tg:
                async def child():
                    try:
                        await aio.sleep(100.0)
                    except aio.CancelledError:
                        child_cancelled.append(True)
                        raise

                tg.create_task(child())
                await aio.sleep(50.0)

        t = aio.create_task(host())
        await aio.sleep(0.05)
        t.cancel()
        with pytest.raises(aio.CancelledError):
            await t
        assert child_cancelled == [True], "children must not be orphaned"
        return True

    assert ms.run(main(), seed=23, time_limit=30)


def test_notify_waiters_cancel_mints_no_phantom_permit():
    # A broadcast (notify_waiters) wakeup consumed by a cancelled waiter
    # must NOT convert into a stored permit (tokio::sync::Notify rule).
    async def main():
        notify = ms.sync.Notify()

        async def waiter_cancelled_late():
            async with aio.timeout(0.05):
                await notify.notified()

        t = aio.create_task(waiter_cancelled_late())
        await aio.sleep(0.01)
        # Resolve the waiter via broadcast, but interrupt it in the same
        # virtual instant window before it resumes.
        t.cancel()
        notify.notify_waiters()
        with pytest.raises(aio.CancelledError):
            await t
        # No permit may exist: a fresh notified() must BLOCK.
        blocked = []

        async def fresh():
            await notify.notified()
            blocked.append("woke")

        aio.create_task(fresh())
        await aio.sleep(0.05)
        assert blocked == [], "phantom permit: notified() returned unsignalled"
        notify.notify_one()
        await aio.sleep(0.01)
        assert blocked == ["woke"]
        return True

    assert ms.run(main(), seed=24, time_limit=30)


def test_broad_except_cannot_swallow_cancellation():
    # Cancelled is a BaseException (the asyncio.CancelledError design):
    # unmodified retry loops with `except Exception` must still be
    # teardown-able by timeout scopes and task cancellation.
    async def main():
        attempts = []

        async def stubborn_retry_loop():
            while True:
                try:
                    attempts.append(1)
                    await aio.sleep(0.01)
                except Exception:   # the swallow-everything anti-pattern
                    continue

        try:
            async with aio.timeout(0.05):
                await stubborn_retry_loop()
            raise AssertionError("expected TimeoutError")
        except TimeoutError:
            pass
        n = len(attempts)
        await aio.sleep(0.05)
        assert len(attempts) == n, "the loop must actually be torn down"
        return True

    assert ms.run(main(), seed=25, time_limit=30)


def test_condition_requires_lock():
    async def main():
        cond = aio.Condition()
        with pytest.raises(RuntimeError, match="un-acquired"):
            await cond.wait()
        with pytest.raises(RuntimeError, match="un-acquired"):
            cond.notify()
        return True

    assert ms.run(main(), seed=26)
