"""Tests for the two-phase-commit device actor (third workload family)."""
import numpy as np
import pytest

from madsim_tpu.engine import (
    DeviceEngine, EngineConfig, TPCActor, TPCDeviceConfig, FAULT_KILL,
    FAULT_RESTART, FAULT_CLOG_LINK, FAULT_UNCLOG_LINK,
)

N = 4


def make_engine(loss=0.0, buggy=False, timeout_us=60_000):
    tcfg = TPCDeviceConfig(n=N, n_txns=6, vote_timeout_us=timeout_us,
                           buggy_presumed_commit=buggy)
    cfg = EngineConfig(n_nodes=N, outbox_cap=N + 1, queue_cap=64,
                       t_limit_us=2_000_000, loss_rate=loss)
    return DeviceEngine(TPCActor(tcfg), cfg)


def test_clean_lossless_commits_or_aborts_atomically():
    eng = make_engine()
    s = eng.run(eng.init(np.arange(512)), max_steps=4000)
    obs = eng.observe(s)
    assert not obs["bug"].any()
    assert not obs["overflow"].any()
    # Every transaction reaches a decision on a lossless network.
    assert ((obs["commits"] + obs["aborts"]) == 6).all()
    # Both outcomes occur across worlds (no-votes happen at ~12.5%/node).
    assert obs["commits"].sum() > 0 and obs["aborts"].sum() > 0
    assert (obs["blocked"] == 0).all()


def test_clean_is_atomic_under_loss_and_coordinator_crash():
    eng = make_engine(loss=0.08)
    faults = np.array([[200_000, FAULT_KILL, 0, 0],
                       [500_000, FAULT_RESTART, 0, 0]], np.int32)
    s = eng.run(eng.init(np.arange(2048), faults=faults), max_steps=6000)
    obs = eng.observe(s)
    assert not obs["bug"].any(), "textbook 2PC must stay atomic under chaos"
    # The blocking window is real: some worlds hold yes-voters without a
    # decision (lost DECIDE or dead coordinator).
    assert (obs["blocked"] > 0).any()


def test_presumed_commit_bug_is_found_under_loss():
    clean = make_engine(loss=0.1)
    buggy = make_engine(loss=0.1, buggy=True)
    sc = clean.run(clean.init(np.arange(2048)), max_steps=6000)
    sb = buggy.run(buggy.init(np.arange(2048)), max_steps=6000)
    oc, ob = clean.observe(sc), buggy.observe(sb)
    assert not oc["bug"].any()
    rate = ob["bug"].mean()
    assert rate > 0.02, f"presumed-commit bug not found (rate={rate})"
    # The failing seed replays: the trace ends at the violating step.
    seed = int(np.flatnonzero(ob["bug"])[0])
    trace = buggy.trace(seed, max_steps=4000)
    raised = [e for e in trace if e.get("bug_raised")]
    assert raised and raised[0]["kind"] in ("Timeout", "Decide", "Vote",
                                            "Prepare", "invariant")


def test_partitioned_no_vote_triggers_buggy_timeout_commit():
    # Deterministic repro shape: clog the link participant-3 -> coordinator
    # for the whole run; 3's no-votes never arrive, the buggy coordinator
    # presumes commit on timeout while 3 aborted unilaterally.
    eng = make_engine(buggy=True)
    faults = np.array([[10_000, FAULT_CLOG_LINK, 3, 0]], np.int32)
    s = eng.run(eng.init(np.arange(512), faults=faults), max_steps=6000)
    obs = eng.observe(s)
    # Only worlds where node 3 actually votes no on some txn violate; with
    # 6 txns at 12.5% that's ~55% of worlds.
    assert obs["bug"].mean() > 0.3
    # And the clean coordinator under the same partition stays atomic.
    eng2 = make_engine()
    s2 = eng2.run(eng2.init(np.arange(512), faults=faults), max_steps=6000)
    assert not eng2.observe(s2)["bug"].any()


def test_deterministic_same_seeds():
    eng = make_engine(loss=0.05, buggy=True)
    a = eng.observe(eng.run(eng.init(np.arange(256)), max_steps=6000))
    b = eng.observe(eng.run(eng.init(np.arange(256)), max_steps=6000))
    for k in a:
        assert np.array_equal(a[k], b[k]), k
