"""detlint tests: golden fixtures, suppression mechanics, the tier-1
self-scan invariant, and sim/real parity drift injection."""
import json
import os
import shutil

import pytest

from madsim_tpu.analysis import (Allowlist, run_escape_pass, run_lint,
                                 run_parity_pass, scan_source)
from madsim_tpu.analysis.cli import main as detlint_main

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "fixtures", "detlint")

# fixture -> {rule code: expected finding count} (golden findings).
GOLDEN = {
    "bad_wallclock.py": {"DET001": 6},
    "bad_timeline.py": {"DET001": 3},
    "bad_entropy.py": {"DET002": 5},
    "bad_threads.py": {"DET003": 3},
    "bad_hostinfo.py": {"DET004": 2},
    "bad_socket.py": {"DET005": 2},
    "bad_idhash.py": {"DET006": 2},
    "bad_profiler.py": {"DET007": 3, "DET001": 2},
    "bad_stale_pragma.py": {"DET900": 1},
}


@pytest.mark.parametrize("fixture,expected", sorted(GOLDEN.items()))
def test_golden_fixture_findings(fixture, expected):
    findings = run_escape_pass(FIXTURES, [fixture])
    counts = {}
    for f in findings:
        counts[f.rule] = counts.get(f.rule, 0) + 1
    assert counts == expected, "\n".join(f.render() for f in findings)


@pytest.mark.parametrize("fixture", sorted(GOLDEN))
def test_cli_exits_nonzero_on_bad_fixture(fixture, capsys):
    rc = detlint_main(["--root", FIXTURES, "--no-parity", fixture])
    assert rc == 1
    out = capsys.readouterr().out
    code = next(iter(GOLDEN[fixture]))
    assert code in out and fixture in out


def test_clean_fixture_and_cli_exit_zero(capsys):
    assert run_escape_pass(FIXTURES, ["clean.py"]) == []
    assert detlint_main(["--root", FIXTURES, "--no-parity", "clean.py"]) == 0


def test_cli_json_output(capsys):
    rc = detlint_main(["--root", FIXTURES, "--no-parity", "--json",
                       "bad_socket.py"])
    assert rc == 1
    data = json.loads(capsys.readouterr().out)
    assert {d["rule"] for d in data} == {"DET005"}
    assert all(d["path"] == "bad_socket.py" and d["line"] > 0 for d in data)


# -- suppression mechanics --------------------------------------------------

def test_pragma_suppresses_same_line_and_line_above():
    src = "import time\n\nt = time.time()  # detlint: allow[DET001]\n"
    assert scan_source(src, "x.py") == []
    src = ("import time\n"
           "# detlint: allow[DET001]\n"
           "t = time.time()\n")
    assert scan_source(src, "x.py") == []


def test_stale_pragma_is_an_error():
    (f,) = scan_source("x = 1  # detlint: allow[DET002]\n", "x.py")
    assert f.rule == "DET900" and "DET002" in f.message


def test_pragma_in_docstring_is_documentation_not_suppression():
    src = ('"""Silence with `# detlint: allow[DET001]` on the line."""\n'
           "import time\n"
           "t = time.time()\n")
    rules = [f.rule for f in scan_source(src, "x.py")]
    assert rules == ["DET001"]  # the docstring neither suppresses nor DET900s


def test_allowlist_prefix_and_rule_scoping():
    from madsim_tpu.analysis import Finding

    allow = Allowlist.parse("pkg/real/\npkg/driver.py:DET003\n")

    assert allow.allows(Finding("pkg/real/net.py", 1, "DET002", ""))
    assert allow.allows(Finding("pkg/driver.py", 1, "DET003", ""))
    assert not allow.allows(Finding("pkg/driver.py", 1, "DET001", ""))
    assert not allow.allows(Finding("pkg/sim.py", 1, "DET002", ""))


# -- the tier-1 invariant ---------------------------------------------------

def test_self_scan_is_clean():
    """The framework passes its own lint (modulo the checked-in allowlist
    and inline pragmas). A regression here means a new nondeterminism
    escape or a sim/real signature drift landed in madsim_tpu/ or tools/."""
    allow = Allowlist.load(os.path.join(REPO, "detlint-allow.txt"))
    findings = run_lint(REPO, ["madsim_tpu", "tools"], allow)
    assert findings == [], "\n".join(f.render() for f in findings)


def test_self_scan_covers_obs_package():
    """The observability package is inside the default scan surface AND
    clean WITHOUT any allowlist — timeline/bundle code must never read
    the wall clock (timestamps are virtual time; DET001 + the
    clock-default decode extension)."""
    from madsim_tpu.analysis.escape import iter_py_files

    files = iter_py_files(REPO, ["madsim_tpu"])
    for rel in ("madsim_tpu/obs/timeline.py", "madsim_tpu/obs/metrics.py",
                "madsim_tpu/obs/bundle.py", "madsim_tpu/obs/cli.py"):
        assert rel in files, f"{rel} escaped the default lint surface"
    findings = run_lint(REPO, ["madsim_tpu/obs"], Allowlist.empty())
    findings = [f for f in findings if f.path.startswith("madsim_tpu/obs")]
    assert findings == [], "\n".join(f.render() for f in findings)


def test_clock_default_decode_calls_flag_only_defaulted_operands():
    """The DET001 decode extension: no-operand forms escape, explicit
    virtual-time operands are pure conversions and stay clean."""
    flagged = scan_source("import time\nx = time.localtime()\n", "x.py")
    assert [f.rule for f in flagged] == ["DET001"]
    assert scan_source("import time\nx = time.localtime(12.5)\n",
                       "x.py") == []
    assert scan_source(
        "import time\nx = time.strftime('%H', time.gmtime(3))\n",
        "x.py") == []
    (f,) = scan_source("import time\nx = time.strftime('%H')\n", "x.py")
    assert f.rule == "DET001"


# -- pass 2: sim/real parity ------------------------------------------------

_PARITY_FILES = [
    "madsim_tpu/net/endpoint.py", "madsim_tpu/net/tcp.py",
    "madsim_tpu/net/netsim.py", "madsim_tpu/fs.py", "madsim_tpu/time.py",
    "madsim_tpu/real/net.py", "madsim_tpu/real/tcp.py",
    "madsim_tpu/real/fs.py",
]


def _copy_tree(tmp_path):
    for rel in _PARITY_FILES:
        dst = tmp_path / rel
        dst.parent.mkdir(parents=True, exist_ok=True)
        shutil.copyfile(os.path.join(REPO, rel), dst)
    return str(tmp_path)


def _edit(tmp_path, rel, old, new):
    p = tmp_path / rel
    src = p.read_text()
    assert old in src, f"drift-injection anchor missing from {rel}: {old!r}"
    p.write_text(src.replace(old, new))


def test_parity_clean_on_repo():
    assert run_parity_pass(REPO) == []


def test_parity_detects_injected_parameter_drift(tmp_path):
    root = _copy_tree(tmp_path)
    _edit(tmp_path, "madsim_tpu/real/tcp.py",
          "async def read_exact(self, n: int)",
          "async def read_exact(self, n: int, strict: bool = True)")
    findings = run_parity_pass(root)
    assert any(f.rule == "PAR001" and "read_exact" in f.message
               for f in findings), findings


def test_parity_detects_renamed_real_method(tmp_path):
    root = _copy_tree(tmp_path)
    _edit(tmp_path, "madsim_tpu/real/fs.py",
          "async def sync_all", "async def fsync_all")
    findings = run_parity_pass(root)
    msgs = [f.message for f in findings if f.rule == "PAR001"]
    # Both directions: sim's sync_all lost its twin, real grew an extra.
    assert any("sync_all" in m and "not in the real twin" in m for m in msgs)
    assert any("fsync_all" in m for m in msgs)


def test_parity_detects_asyncness_drift(tmp_path):
    root = _copy_tree(tmp_path)
    _edit(tmp_path, "madsim_tpu/real/tcp.py",
          "    def close(self) -> None:\n        self._writer.close()",
          "    async def close(self) -> None:\n        self._writer.close()")
    findings = run_parity_pass(root)
    assert any(f.rule == "PAR001" and "async-ness" in f.message
               and "close" in f.message for f in findings), findings


def test_parity_dispatch_check_flags_missing_is_real(tmp_path):
    (tmp_path / "madsim_tpu").mkdir(parents=True)
    (tmp_path / "madsim_tpu" / "time.py").write_text(
        '__all__ = ["sleep", "monotonic"]\n'
        "def monotonic():\n"
        "    return 0.0\n"
        "def sleep(seconds):\n"
        "    from .core.backend import is_real\n"
        "    if is_real():\n"
        "        return None\n"
        "    return None\n")
    findings = run_parity_pass(str(tmp_path))
    assert [f.rule for f in findings] == ["PAR002"]
    assert "monotonic" in findings[0].message
