"""Checkpoint/resume: a split run must be bit-identical to an unbroken one.

The crosscheck-style assertion VERDICT r2 item 9 specifies: save mid-run,
reload (fresh engine object — nothing shared), continue, compare every
state leaf bitwise against a run that never stopped.
"""
import jax
import numpy as np
import pytest

from madsim_tpu.engine import (
    DeviceEngine, EngineConfig, RaftActor, RaftDeviceConfig,
    CheckpointError, load_checkpoint, save_checkpoint,
)

RCFG = RaftDeviceConfig(n=3, n_proposals=2)
ECFG = EngineConfig(n_nodes=3, outbox_cap=4, t_limit_us=2_000_000)


def _leaves_equal(a, b) -> bool:
    return all(np.array_equal(np.asarray(x), np.asarray(y))
               for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)))


def test_split_run_bit_identical(tmp_path):
    path = tmp_path / "ckpt.npz"
    eng = DeviceEngine(RaftActor(RCFG), ECFG)

    unbroken = eng.run_steps(eng.init(np.arange(16)), 800)

    half = eng.run_steps(eng.init(np.arange(16)), 400)
    save_checkpoint(eng, half, path)
    # Fresh engine object: nothing survives but the file.
    eng2 = DeviceEngine(RaftActor(RCFG), ECFG)
    resumed = load_checkpoint(eng2, path)
    assert _leaves_equal(half, resumed), "load must restore state bitwise"
    finished = eng2.run_steps(resumed, 400)
    assert _leaves_equal(unbroken, finished), \
        "a split run must be bit-identical to an unbroken run"


def test_checkpoint_rejects_wrong_config(tmp_path):
    path = tmp_path / "ckpt.npz"
    eng = DeviceEngine(RaftActor(RCFG), ECFG)
    save_checkpoint(eng, eng.init(np.arange(4)), path)
    other = DeviceEngine(
        RaftActor(RaftDeviceConfig(n=5, log_cap=16)),
        EngineConfig(n_nodes=5, outbox_cap=6))
    with pytest.raises(CheckpointError, match="different engine config"):
        load_checkpoint(other, path)
    # Same EngineConfig but different ACTOR config must also be rejected
    # (same shapes — only the fingerprint can catch it).
    tweaked = DeviceEngine(
        RaftActor(RaftDeviceConfig(n=3, n_proposals=2, heartbeat_us=10_000)),
        ECFG)
    with pytest.raises(CheckpointError, match="different engine config"):
        load_checkpoint(tweaked, path)


def test_sweep_resume_rejects_different_seeds(tmp_path):
    from madsim_tpu.parallel.sweep import sweep

    path = str(tmp_path / "sweep.npz")
    eng = DeviceEngine(RaftActor(RCFG), ECFG)
    sweep(None, ECFG, np.arange(100, 124), engine=eng, chunk_steps=64,
          max_steps=64, checkpoint_path=path)
    with pytest.raises(CheckpointError, match="seeds_sha256"):
        sweep(None, ECFG, np.arange(24), engine=eng, chunk_steps=64,
              max_steps=64, checkpoint_path=path, resume=True)


def test_sweep_resume_rejects_wrong_world_count(tmp_path):
    """Defense-in-depth behind the seeds-hash gate: a checkpoint whose
    metadata matches but whose state holds a different world count must
    raise CheckpointError, not shard a mis-shaped batch. (Reachable only
    via a forged/corrupted checkpoint — the seeds hash normally pins the
    padded width — so the file is forged here.)"""
    import hashlib

    from madsim_tpu.parallel.sweep import sweep

    path = str(tmp_path / "sweep.npz")
    seeds = np.arange(24)
    eng = DeviceEngine(RaftActor(RCFG), ECFG)
    # Metadata for the 24-seed sweep, wrapped around a 16-world state.
    meta = {
        "seeds_sha256": hashlib.sha256(
            seeds.astype(np.uint64).tobytes()).hexdigest(),
        "faults_sha256": hashlib.sha256(b"none").hexdigest(),
    }
    save_checkpoint(eng, eng.init(np.arange(16)), path, extra_meta=meta)
    with pytest.raises(CheckpointError, match="16 worlds"):
        sweep(None, ECFG, seeds, engine=eng, chunk_steps=64,
              max_steps=64, checkpoint_path=path, resume=True)


def test_sweep_resumes_from_checkpoint(tmp_path):
    from madsim_tpu.parallel.sweep import sweep

    path = str(tmp_path / "sweep.npz")
    seeds = np.arange(24)
    eng = DeviceEngine(RaftActor(RCFG), ECFG)
    full = sweep(None, ECFG, seeds, engine=eng, chunk_steps=128,
                 max_steps=4_000)

    # Interrupted sweep: only 2 chunks, checkpointing as it goes.
    eng2 = DeviceEngine(RaftActor(RCFG), ECFG)
    partial = sweep(None, ECFG, seeds, engine=eng2, chunk_steps=128,
                    max_steps=256, checkpoint_path=path,
                    checkpoint_every_chunks=1)
    assert partial.steps_run == 256
    # "Process restart": new engine, resume from disk, run to completion.
    eng3 = DeviceEngine(RaftActor(RCFG), ECFG)
    resumed = sweep(None, ECFG, seeds, engine=eng3, chunk_steps=128,
                    max_steps=4_000, checkpoint_path=path, resume=True)

    for key in full.observations:
        assert np.array_equal(full.observations[key],
                              resumed.observations[key]), key
    assert np.array_equal(full.bug, resumed.bug)
