"""Checkpoint/resume: a split run must be bit-identical to an unbroken one.

The crosscheck-style assertion VERDICT r2 item 9 specifies: save mid-run,
reload (fresh engine object — nothing shared), continue, compare every
state leaf bitwise against a run that never stopped.
"""
import jax
import numpy as np
import pytest

from madsim_tpu.engine import (
    DeviceEngine, EngineConfig, RaftActor, RaftDeviceConfig,
    CheckpointError, load_checkpoint, save_checkpoint,
)

RCFG = RaftDeviceConfig(n=3, n_proposals=2)
ECFG = EngineConfig(n_nodes=3, outbox_cap=4, t_limit_us=2_000_000)


def _leaves_equal(a, b) -> bool:
    return all(np.array_equal(np.asarray(x), np.asarray(y))
               for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)))


def test_split_run_bit_identical(tmp_path):
    path = tmp_path / "ckpt.npz"
    eng = DeviceEngine(RaftActor(RCFG), ECFG)

    unbroken = eng.run_steps(eng.init(np.arange(16)), 800)

    half = eng.run_steps(eng.init(np.arange(16)), 400)
    save_checkpoint(eng, half, path)
    # Fresh engine object: nothing survives but the file.
    eng2 = DeviceEngine(RaftActor(RCFG), ECFG)
    resumed = load_checkpoint(eng2, path)
    assert _leaves_equal(half, resumed), "load must restore state bitwise"
    finished = eng2.run_steps(resumed, 400)
    assert _leaves_equal(unbroken, finished), \
        "a split run must be bit-identical to an unbroken run"


def test_checkpoint_rejects_wrong_config(tmp_path):
    path = tmp_path / "ckpt.npz"
    eng = DeviceEngine(RaftActor(RCFG), ECFG)
    save_checkpoint(eng, eng.init(np.arange(4)), path)
    other = DeviceEngine(
        RaftActor(RaftDeviceConfig(n=5, log_cap=16)),
        EngineConfig(n_nodes=5, outbox_cap=6))
    with pytest.raises(CheckpointError, match="different engine config"):
        load_checkpoint(other, path)
    # Same EngineConfig but different ACTOR config must also be rejected
    # (same shapes — only the fingerprint can catch it).
    tweaked = DeviceEngine(
        RaftActor(RaftDeviceConfig(n=3, n_proposals=2, heartbeat_us=10_000)),
        ECFG)
    with pytest.raises(CheckpointError, match="different engine config"):
        load_checkpoint(tweaked, path)


def test_sweep_resume_rejects_different_seeds(tmp_path):
    from madsim_tpu.parallel.sweep import sweep

    path = str(tmp_path / "sweep.npz")
    eng = DeviceEngine(RaftActor(RCFG), ECFG)
    sweep(None, ECFG, np.arange(100, 124), engine=eng, chunk_steps=64,
          max_steps=64, checkpoint_path=path)
    with pytest.raises(CheckpointError, match="seeds_sha256"):
        sweep(None, ECFG, np.arange(24), engine=eng, chunk_steps=64,
              max_steps=64, checkpoint_path=path, resume=True)


def test_sweep_resume_rejects_wrong_world_count(tmp_path):
    """Defense-in-depth behind the seeds-hash gate: a checkpoint whose
    metadata matches but whose state holds a different world count must
    raise CheckpointError, not shard a mis-shaped batch. (Reachable only
    via a forged/corrupted checkpoint — the seeds hash normally pins the
    padded width — so the file is forged here.)"""
    import hashlib

    from madsim_tpu.parallel.sweep import sweep

    path = str(tmp_path / "sweep.npz")
    seeds = np.arange(24)
    eng = DeviceEngine(RaftActor(RCFG), ECFG)
    # Metadata for the 24-seed sweep, wrapped around a 16-world state.
    meta = {
        "seeds_sha256": hashlib.sha256(
            seeds.astype(np.uint64).tobytes()).hexdigest(),
        "faults_sha256": hashlib.sha256(b"none").hexdigest(),
    }
    save_checkpoint(eng, eng.init(np.arange(16)), path, extra_meta=meta)
    with pytest.raises(CheckpointError, match="16 worlds"):
        sweep(None, ECFG, seeds, engine=eng, chunk_steps=64,
              max_steps=64, checkpoint_path=path, resume=True)


@pytest.fixture(scope="module")
def heng():
    """One shared engine for the hardening tests below: they exercise
    file-level behavior (fsync ordering, torn files, aux arrays), so a
    single compiled engine + one batch shape keeps them cheap."""
    return DeviceEngine(RaftActor(RCFG), ECFG)


def test_crash_between_write_and_rename_keeps_previous(tmp_path,
                                                       monkeypatch, heng):
    """A writer dying between the tmp write and the rename must leave
    the PREVIOUS checkpoint intact and loadable — the atomic-replace
    contract under the exact crash the fsync+rename dance exists for."""
    from madsim_tpu.engine import checkpoint as ckpt_mod

    path = tmp_path / "ckpt.npz"
    eng = heng
    half = eng.run_steps(eng.init(np.arange(8)), 200)
    save_checkpoint(eng, half, path)

    # A different state for the crashing re-save. Built from a fresh
    # init, NOT by stepping ``half``: run_steps donates its input, and
    # on the CPU backend host views of donated buffers can alias the
    # memory XLA then overwrites — ``half`` must stay alive untouched
    # for the comparison below.
    later = eng.run_steps(eng.init(np.arange(8)), 400)

    def dying_replace(src, dst):
        raise OSError("simulated crash between write and rename")

    monkeypatch.setattr(ckpt_mod.os, "replace", dying_replace)
    with pytest.raises(OSError, match="simulated crash"):
        save_checkpoint(eng, later, path)
    monkeypatch.undo()

    # The published path still holds the FIRST snapshot, bit-intact, and
    # resume proceeds from it to the same place an unbroken run reaches.
    recovered = load_checkpoint(eng, path)
    assert _leaves_equal(half, recovered), \
        "a crashed re-save must not touch the previous checkpoint"
    assert _leaves_equal(later, eng.run_steps(recovered, 200))


def test_save_fsyncs_before_rename(tmp_path, monkeypatch, heng):
    """Durability ordering: the tmp file's bytes must be fsync'd BEFORE
    os.replace publishes the name (without it, a machine crash can
    publish a name pointing at unflushed, torn bytes)."""
    from madsim_tpu.engine import checkpoint as ckpt_mod

    order = []
    real_fsync, real_replace = ckpt_mod.os.fsync, ckpt_mod.os.replace
    monkeypatch.setattr(ckpt_mod.os, "fsync",
                        lambda fd: (order.append("fsync"), real_fsync(fd)))
    monkeypatch.setattr(
        ckpt_mod.os, "replace",
        lambda a, b: (order.append("replace"), real_replace(a, b)))
    save_checkpoint(heng, heng.init(np.arange(8)), tmp_path / "c.npz")
    assert "fsync" in order and "replace" in order
    assert order.index("fsync") < order.index("replace")


def test_corrupt_checkpoint_reports_path_and_recovery(tmp_path, heng):
    """Truncated and garbage files raise CheckpointError naming the file
    and the recovery options — never a bare zipfile/numpy internal."""
    path = tmp_path / "ckpt.npz"
    eng = heng
    save_checkpoint(eng, eng.init(np.arange(8)), path)
    good = path.read_bytes()

    # Truncation (torn write) and garbage (disk corruption).
    for bad in (good[:137], b"not an npz at all"):
        path.write_bytes(bad)
        with pytest.raises(CheckpointError) as exc_info:
            load_checkpoint(eng, path)
        msg = str(exc_info.value)
        assert str(path) in msg, "must name the corrupt file"
        assert "recovery options" in msg
        assert "zipfile" not in msg.lower().replace("badzipfile", "")


def test_sweep_resume_on_corrupt_checkpoint_reports(tmp_path, heng):
    """resume=True over a corrupt file surfaces the same actionable
    CheckpointError (path + recovery options) through the sweep."""
    from madsim_tpu.parallel.sweep import sweep

    path = tmp_path / "sweep.npz"
    eng = heng
    sweep(None, ECFG, np.arange(8), engine=eng, chunk_steps=64,
          max_steps=64, checkpoint_path=str(path))
    path.write_bytes(path.read_bytes()[:100])
    with pytest.raises(CheckpointError, match="recovery options"):
        sweep(None, ECFG, np.arange(8), engine=eng, chunk_steps=64,
              max_steps=64, checkpoint_path=str(path), resume=True)


def test_checkpoint_extra_arrays_round_trip(tmp_path, heng):
    """save(extra_arrays=...) / load(with_aux=True): named host arrays
    ride beside the state leaves (the recycled sweep's cursor/index/
    retired-observation carrier); plain loads ignore them."""
    path = tmp_path / "aux.npz"
    eng = heng
    state = eng.init(np.arange(8))
    aux_in = {"cursor": np.int64(17),
              "idx": np.arange(8, dtype=np.int32),
              "ret_steps": np.asarray([5, 9], np.int32)}
    save_checkpoint(eng, state, path, extra_arrays=aux_in)
    loaded, aux = load_checkpoint(eng, path, with_aux=True)
    assert _leaves_equal(state, loaded)
    assert set(aux) == set(aux_in)
    for k in aux_in:
        np.testing.assert_array_equal(aux[k], aux_in[k])
    # Backward-shaped call: aux invisible unless asked for.
    assert _leaves_equal(state, load_checkpoint(eng, path))


def test_sweep_resumes_from_checkpoint(tmp_path):
    from madsim_tpu.parallel.sweep import sweep

    path = str(tmp_path / "sweep.npz")
    seeds = np.arange(24)
    eng = DeviceEngine(RaftActor(RCFG), ECFG)
    full = sweep(None, ECFG, seeds, engine=eng, chunk_steps=128,
                 max_steps=4_000)

    # Interrupted sweep: only 2 chunks, checkpointing as it goes.
    eng2 = DeviceEngine(RaftActor(RCFG), ECFG)
    partial = sweep(None, ECFG, seeds, engine=eng2, chunk_steps=128,
                    max_steps=256, checkpoint_path=path,
                    checkpoint_every_chunks=1)
    assert partial.steps_run == 256
    # "Process restart": new engine, resume from disk, run to completion.
    eng3 = DeviceEngine(RaftActor(RCFG), ECFG)
    resumed = sweep(None, ECFG, seeds, engine=eng3, chunk_steps=128,
                    max_steps=4_000, checkpoint_path=path, resume=True)

    for key in full.observations:
        assert np.array_equal(full.observations[key],
                              resumed.observations[key]), key
    assert np.array_equal(full.bug, resumed.bug)
