"""Dual-mode tests: the SAME world code runs in sim and real mode.

This is the repo's analog of the reference's dual-mode CI matrix
(`ci.yml:66-108` — every crate passes both as real tokio code and under
``--cfg madsim``). Each world below is one async function written against
the madsim_tpu facades; the ``mode`` fixture runs it once inside a seeded
simulation and once on the production backend (``MADSIM_BACKEND=real`` →
asyncio + framed TCP over real loopback sockets,
`madsim/src/std/net/tcp.rs:20-324` analog).
"""
import dataclasses
import os
from pathlib import Path

import pytest

import madsim_tpu as ms
from madsim_tpu import time as mtime
from madsim_tpu.net import Endpoint, rpc


@dataclasses.dataclass
class Add:
    a: int
    b: int


@dataclasses.dataclass
class Unhandled:
    x: int = 0


@pytest.fixture(params=["sim", "real", "real-uds", "real-shm"])
def mode(request, monkeypatch, tmp_path):
    if request.param.startswith("real"):
        monkeypatch.setenv("MADSIM_BACKEND", "real")
    else:
        monkeypatch.delenv("MADSIM_BACKEND", raising=False)
    if request.param in ("real-uds", "real-shm"):
        # Alternative real wire transports behind the same Endpoint API —
        # the reference's ucx/erpc feature-flag analogs: Unix sockets, and
        # the shm bulk leg (UDS control + shared-memory rings for large
        # payloads, docs/transports.md).
        monkeypatch.setenv("MADSIM_REAL_TRANSPORT",
                           request.param.removeprefix("real-"))
        monkeypatch.setenv("MADSIM_UDS_DIR", str(tmp_path / "uds"))
    else:
        monkeypatch.delenv("MADSIM_REAL_TRANSPORT", raising=False)
    return request.param


# ---------------------------------------------------------------------------
# Worlds (mode-agnostic application code)
# ---------------------------------------------------------------------------

async def tag_matching_world():
    ep1 = await Endpoint.bind("127.0.0.1:0")
    ep2 = await Endpoint.bind("127.0.0.1:0")
    addr2 = ep2.local_addr()
    await ep1.send_to(addr2, 7, b"seven")
    await ep1.send_to(addr2, 5, b"five")
    # Tag matching must deliver out of arrival order.
    data5, from5 = await ep2.recv_from(5)
    data7, from7 = await ep2.recv_from(7)
    assert data5 == b"five" and data7 == b"seven"
    assert from5 == ep1.local_addr() and from7 == ep1.local_addr()
    # Non-bytes payloads round-trip too (pickled on the wire in real mode).
    await ep2.send_to(ep1.local_addr(), 1, {"k": [1, 2, 3]})
    obj, _ = await ep1.recv_from(1)
    assert obj == {"k": [1, 2, 3]}
    ep1.close()
    ep2.close()
    return True


async def rpc_world():
    server = await Endpoint.bind("127.0.0.1:0")

    async def add(req):
        return req.a + req.b

    rpc.add_rpc_handler(server, Add, add)
    client = await Endpoint.bind("127.0.0.1:0")
    results = []
    for i in range(10):
        r = await rpc.call(client, server.local_addr(), Add(i, 2 * i),
                           timeout=5.0)
        results.append(r)
    assert results == [3 * i for i in range(10)]
    # Timeout path: no handler registered for this request type.
    try:
        await rpc.call(client, server.local_addr(), Unhandled(), timeout=0.2)
        raise AssertionError("expected TimeoutError")
    except TimeoutError:
        pass
    server.close()
    client.close()
    return True


async def primitives_world():
    # Virtual (or OS) clock + sleep.
    t0 = mtime.monotonic()
    await mtime.sleep(0.01)
    assert mtime.monotonic() - t0 >= 0.009
    # Tasks + sync primitives over the same facades.
    ch = ms.sync.Channel()
    done = ms.sync.SimFuture()

    async def producer():
        for i in range(5):
            ch.send(i)
            await mtime.sleep(0.001)
        done.set_result("done")

    handle = ms.task.spawn(producer())
    got = [await ch.recv() for _ in range(5)]
    assert got == [0, 1, 2, 3, 4]
    assert await done == "done"
    await handle
    # Locks and events.
    ev = ms.sync.Event()
    lock = ms.sync.Lock()

    async def setter():
        async with lock:
            await mtime.sleep(0.001)
        ev.set()

    ms.task.spawn(setter())
    await ev.wait()
    # Randomness: both backends expose the same surface.
    rng = ms.rand.thread_rng()
    vals = [rng.gen_range(0, 100) for _ in range(8)]
    assert all(0 <= v < 100 for v in vals)
    assert len(rng.gen_bytes(16)) == 16
    # Timeout wrapping a sync future that never resolves.
    try:
        await mtime.timeout(0.02, ms.sync.SimFuture())
        raise AssertionError("expected TimeoutError")
    except TimeoutError:
        pass
    # TaskGroup works on both backends (real mode: no sim executor).
    from madsim_tpu.shims import aio

    order = []

    async with aio.TaskGroup() as tg:
        async def member(i, d):
            await mtime.sleep(d)
            order.append(i)

        tg.create_task(member(0, 0.02))
        tg.create_task(member(1, 0.01))
    assert sorted(order) == [0, 1]
    return True


async def connect1_world():
    """Connection-oriented channels (connect1/accept1) — ordered duplex
    with EOF propagation, in sim AND over real transports."""
    from madsim_tpu import task

    a = await Endpoint.bind("127.0.0.1:0")
    b = await Endpoint.bind("127.0.0.1:0")

    async def server():
        tx, rx, src = await b.accept1()
        n = 0
        while True:
            msg = await rx.recv_or_eof()
            if msg is None:
                break
            await tx.send(("echo", msg))
            n += 1
        tx.close()
        return n, src

    srv = task.spawn(server())
    tx, rx = await a.connect1(b.local_addr())
    for i in range(5):
        await tx.send({"seq": i})
        tag, payload = await rx.recv()
        assert tag == "echo" and payload == {"seq": i}
    tx.close()  # half-close: the server sees EOF and closes its side
    assert await rx.recv_or_eof() is None
    n, src = await srv
    assert n == 5
    assert src == a.local_addr()
    # The strict receive raises at EOF, and sends on a closed channel
    # raise ConnectionReset — identical contract in sim and real mode.
    from madsim_tpu.net.netsim import ConnectionReset
    try:
        await rx.recv()
        raise AssertionError("recv at EOF must raise")
    except ConnectionReset:
        pass
    try:
        await tx.send("late")
        raise AssertionError("send after close must raise")
    except ConnectionReset:
        pass
    # Closing the endpoint wakes a blocked accept1 with ConnectionReset.
    async def acceptor():
        try:
            await b.accept1()
            return "accepted"
        except ConnectionReset:
            return "reset"

    h = task.spawn(acceptor())
    from madsim_tpu import time as mt
    await mt.sleep(0.01)
    b.close()
    assert await h == "reset"
    a.close()
    return True


async def tcp_world():
    from madsim_tpu.net import TcpListener, TcpStream

    listener = await TcpListener.bind("127.0.0.1:0")
    addr = listener.local_addr()

    async def server():
        stream, peer = await listener.accept()
        data = await stream.read_exact(11)
        await stream.write_all(data.upper())
        stream.close()

    h = ms.task.spawn(server())
    client = await TcpStream.connect(addr)
    await client.write_all(b"hello world")
    assert await client.read_exact(11) == b"HELLO WORLD"
    assert await client.read() == b""  # orderly EOF
    client.close()
    await h
    listener.close()
    return True


async def postgres_world():
    # The wire-faithful v3 protocol runs over whichever TCP backend is
    # active: simulated byte streams in-sim, real loopback sockets in
    # production mode (the madsim-tokio-postgres deployment claim).
    from madsim_tpu.shims import postgres

    server = postgres.SimPostgresServer()
    h = ms.task.spawn(server.serve(("127.0.0.1", 0)))
    # Readiness = the listener exists and reports its bound ephemeral port
    # (no fixed port: parallel test runs must not collide).
    while server._listener is None:
        await mtime.sleep(0.01)
    port = server._listener.local_addr()[1]
    conn = await postgres.connect("127.0.0.1", port)
    await conn.execute("CREATE TABLE t (k, v)")
    ins = await conn.prepare("INSERT INTO t VALUES ($1, $2)")
    async with conn.transaction():
        await conn.execute_prepared(ins, ["a", "1"])
    rows = await conn.query("SELECT v FROM t WHERE k = 'a'")
    assert rows[0][0] == "1"
    await conn.close()
    h.abort()
    server.close()
    return True


async def fs_world(path: str):
    await ms.fs.write(path, b"hello world")
    f = await ms.fs.File.open(path)
    assert await f.read_at(6, 5) == b"world"
    await f.write_all_at(b"W", 6)
    await f.sync_all()
    meta = await f.metadata()
    assert meta.len == 11
    assert await ms.fs.read(path) == b"hello World"
    await ms.fs.remove_file(path)
    return True


# ---------------------------------------------------------------------------
# Tests
# ---------------------------------------------------------------------------

def test_tag_matching(mode):
    assert ms.run(tag_matching_world(), seed=1)


def test_rpc_pingpong(mode):
    assert ms.run(rpc_world(), seed=2, time_limit=120.0)


def test_primitives(mode):
    assert ms.run(primitives_world(), seed=3)


def test_connect1_channels(mode):
    assert ms.run(connect1_world(), seed=4, time_limit=120.0)


def test_tcp_streams(mode):
    assert ms.run(tcp_world(), seed=6, time_limit=60)


def test_postgres_over_both_backends(mode):
    assert ms.run(postgres_world(), seed=7, time_limit=120)


def test_fs(mode):
    path = f"/tmp/madsim_dualmode_{os.getpid()}.bin"
    try:
        assert ms.run(fs_world(path), seed=4)
    finally:
        if os.path.exists(path):
            os.remove(path)


def test_real_mode_is_not_deterministic_and_sim_is(monkeypatch):
    # The whole point of the split: sim draws are seed-deterministic,
    # real draws come from OS entropy.
    async def draws():
        rng = ms.rand.thread_rng()
        return [rng.next_u64() for _ in range(4)]

    monkeypatch.delenv("MADSIM_BACKEND", raising=False)
    a = ms.run(draws(), seed=7)
    b = ms.run(draws(), seed=7)
    assert a == b
    monkeypatch.setenv("MADSIM_BACKEND", "real")
    c = ms.run(draws(), seed=7)
    d = ms.run(draws(), seed=7)
    assert c != d


def test_real_mode_cross_process_rpc(monkeypatch, tmp_path):
    # The production deployment shape: server and client in SEPARATE OS
    # processes over real TCP — same facade code as the sim worlds above.
    import subprocess
    import sys as _sys
    import textwrap

    server_src = textwrap.dedent("""
        import dataclasses, os, sys
        sys.path.insert(0, %r)
        os.environ["MADSIM_BACKEND"] = "real"
        import madsim_tpu as ms
        from madsim_tpu.net import Endpoint, rpc

        @dataclasses.dataclass
        class Add:
            a: int
            b: int
        Add.__module__ = "__main__"; Add.__qualname__ = "Add"

        async def main():
            ep = await Endpoint.bind("127.0.0.1:0")
            async def add(req):
                return req.a + req.b
            rpc.add_rpc_handler(ep, Add, add)
            print(f"PORT {ep.local_addr()[1]}", flush=True)
            await ms.time.sleep(30)

        ms.run(main())
    """) % str(Path(__file__).resolve().parent.parent)

    proc = subprocess.Popen([_sys.executable, "-c", server_src],
                            stdout=subprocess.PIPE, text=True)
    try:
        line = proc.stdout.readline()
        assert line.startswith("PORT "), f"server failed: {line!r}"
        port = int(line.split()[1])
        monkeypatch.setenv("MADSIM_BACKEND", "real")

        # The client's Add must pickle to the same path as the server's.
        import __main__ as main_mod

        @dataclasses.dataclass
        class Add:
            a: int
            b: int

        Add.__module__ = "__main__"
        Add.__qualname__ = "Add"
        had = getattr(main_mod, "Add", None)
        main_mod.Add = Add
        try:
            async def client():
                ep = await Endpoint.bind("127.0.0.1:0")
                total = 0
                for i in range(20):
                    total += await rpc.call(ep, f"127.0.0.1:{port}",
                                            Add(i, i), timeout=5.0)
                ep.close()
                return total

            assert ms.run(client()) == 2 * sum(range(20))
        finally:
            if had is None:
                delattr(main_mod, "Add")
            else:
                main_mod.Add = had
    finally:
        proc.kill()
        proc.wait()


def test_real_peer_restart_reconnects(monkeypatch):
    # Regression (round-4 review): a peer endpoint closing must evict the
    # cached sender connection at EOF, so a send after the peer rebinds the
    # same port reconnects instead of writing into the dead socket.
    monkeypatch.setenv("MADSIM_BACKEND", "real")

    async def main():
        import asyncio

        a = await Endpoint.bind("127.0.0.1:0")
        b = await Endpoint.bind("127.0.0.1:0")
        addr = b.local_addr()
        await a.send_to(addr, 7, b"one")
        assert (await b.recv_from(7))[0] == b"one"
        b.close()
        await asyncio.sleep(0.1)  # let the FIN reach a's protocol
        b2 = await Endpoint.bind(f"127.0.0.1:{addr[1]}")
        await a.send_to(addr, 7, b"two")
        data, _ = await b2.recv_from(7)
        a.close()
        b2.close()
        return data

    assert ms.run(main()) == b"two"


def test_sim_wins_inside_runtime(monkeypatch):
    # MADSIM_BACKEND=real must NOT leak into a running simulation: inside a
    # Runtime the sim backend always wins (tests stay simulated).
    monkeypatch.setenv("MADSIM_BACKEND", "real")

    async def world():
        from madsim_tpu.core.backend import is_real

        assert not is_real()
        t0 = mtime.monotonic()
        await mtime.sleep(10.0)  # virtual: completes instantly
        return mtime.monotonic() - t0

    rt = ms.Runtime(seed=5)
    assert rt.block_on(world()) >= 10.0


def test_shm_bulk_payloads_ring_wrap_and_fallback(monkeypatch, tmp_path):
    """The shm leg's bulk path: >=32 KiB payloads ride the ring (including
    wrap-around and pickled containers with hoisted buffers); an
    arena too small for the payload falls back to the inline socket path
    instead of failing."""
    monkeypatch.setenv("MADSIM_BACKEND", "real")
    monkeypatch.setenv("MADSIM_REAL_TRANSPORT", "shm")
    monkeypatch.setenv("MADSIM_UDS_DIR", str(tmp_path / "uds"))
    monkeypatch.setenv("MADSIM_SHM_ARENA", str(1 << 20))  # tiny: force wraps

    async def world():
        a = await Endpoint.bind("127.0.0.1:0")
        b = await Endpoint.bind("127.0.0.1:0")
        big = bytes(range(256)) * 1024            # 256 KiB, ring-sized
        huge = b"\xcd" * (2 << 20)                # 2 MiB > arena: fallback
        for i in range(12):                       # 3 MiB through a 1 MiB ring
            await a.send_to(b.local_addr(), 1, big)
            data, _ = await b.recv_from(1)
            assert data == big
        await a.send_to(b.local_addr(), 2, {"blob": big, "i": 7})
        data, _ = await b.recv_from(2)
        assert data["blob"] == big and data["i"] == 7
        await a.send_to(b.local_addr(), 3, huge)  # inline fallback
        data, _ = await b.recv_from(3)
        assert data == huge
        a.close()
        b.close()
        return True

    assert ms.run(world())


def test_shm_hello_survives_first_alloc_failure(monkeypatch, tmp_path):
    """If the connection's FIRST bulk payload exceeds the arena, the
    one-time HELLO must still reach the peer (on the inline fallback) or
    every later in-range REF would be fatal."""
    monkeypatch.setenv("MADSIM_BACKEND", "real")
    monkeypatch.setenv("MADSIM_REAL_TRANSPORT", "shm")
    monkeypatch.setenv("MADSIM_UDS_DIR", str(tmp_path / "uds"))
    monkeypatch.setenv("MADSIM_SHM_ARENA", str(256 << 10))

    async def world():
        a = await Endpoint.bind("127.0.0.1:0")
        b = await Endpoint.bind("127.0.0.1:0")
        huge = b"\xee" * (1 << 20)   # > arena: inline fallback, carries HELLO
        mid = b"\xaf" * (128 << 10)  # fits: must ride the ring fine
        await a.send_to(b.local_addr(), 1, huge)
        data, _ = await b.recv_from(1)
        assert data == huge
        for _ in range(6):
            await a.send_to(b.local_addr(), 2, mid)
            data, _ = await b.recv_from(2)
            assert data == mid
        a.close()
        b.close()
        return True

    assert ms.run(world())


def test_shm_cross_process_bulk_rpc(monkeypatch, tmp_path):
    """The shm leg's reason to exist: server and client in SEPARATE OS
    processes, bulk payloads riding the shared-memory ring (UDS control
    plane), acks releasing ring space across process boundaries."""
    import subprocess
    import sys as _sys
    import textwrap

    uds_dir = str(tmp_path / "uds")
    server_src = textwrap.dedent("""
        import dataclasses, os, sys
        sys.path.insert(0, %r)
        os.environ["MADSIM_BACKEND"] = "real"
        os.environ["MADSIM_REAL_TRANSPORT"] = "shm"
        os.environ["MADSIM_UDS_DIR"] = %r
        import madsim_tpu as ms
        from madsim_tpu.net import Endpoint, rpc

        @dataclasses.dataclass
        class Blob:
            data: bytes
        Blob.__module__ = "__main__"; Blob.__qualname__ = "Blob"

        async def main():
            ep = await Endpoint.bind("127.0.0.1:0")
            async def rev(req):
                return Blob(req.data[::-1])
            rpc.add_rpc_handler(ep, Blob, rev)
            print(f"PORT {ep.local_addr()[1]}", flush=True)
            await ms.time.sleep(60)

        ms.run(main())
    """) % (str(Path(__file__).resolve().parent.parent), uds_dir)

    proc = subprocess.Popen([_sys.executable, "-c", server_src],
                            stdout=subprocess.PIPE, text=True)
    try:
        line = proc.stdout.readline()
        assert line.startswith("PORT "), f"server failed: {line!r}"
        port = int(line.split()[1])
        monkeypatch.setenv("MADSIM_BACKEND", "real")
        monkeypatch.setenv("MADSIM_REAL_TRANSPORT", "shm")
        monkeypatch.setenv("MADSIM_UDS_DIR", uds_dir)

        import __main__ as main_mod

        @dataclasses.dataclass
        class Blob:
            data: bytes

        Blob.__module__ = "__main__"
        Blob.__qualname__ = "Blob"
        had = getattr(main_mod, "Blob", None)
        main_mod.Blob = Blob
        try:
            async def client():
                ep = await Endpoint.bind("127.0.0.1:0")
                payload = bytes(range(256)) * 512  # 128 KiB: the ring path
                for _ in range(12):                # > one ring's worth
                    r = await rpc.call(ep, f"127.0.0.1:{port}",
                                       Blob(payload), timeout=10.0)
                    assert r.data == payload[::-1]
                ep.close()
                return True

            assert ms.run(client())
        finally:
            if had is None:
                delattr(main_mod, "Blob")
            else:
                main_mod.Blob = had
    finally:
        proc.kill()
        proc.wait()
