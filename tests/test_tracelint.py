"""tracelint tests: golden fixtures for every TRC rule and the hot-loop
sync discipline (DET008/DET009), the donation-drop mutation, the budget
ledger gates, and the tier-1 self-scan of the registered hot-path
programs.

Compile discipline: only the donation-mutation and budget-gate tests pay
fresh XLA compiles (the persistent cache must be bypassed for honest
alias/cost statistics — see analysis/budgets.py); everything else is
trace-only (make_jaxpr), which costs seconds.
"""
import importlib.util
import json
import os
import warnings

import numpy as np
import pytest

from madsim_tpu.analysis import Allowlist, run_lint, scan_source
from madsim_tpu.analysis import budgets as B
from madsim_tpu.analysis import tracelint as TL
from madsim_tpu.analysis.cli import main as detlint_main
from madsim_tpu.analysis.cli import main_trace

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "fixtures", "tracelint")


def _load_fixture_module():
    spec = importlib.util.spec_from_file_location(
        "tracelint_bad_programs", os.path.join(FIXTURES, "bad_programs.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def bad():
    return _load_fixture_module()


def _trace_rules(fn, *args):
    import jax

    jaxpr = jax.make_jaxpr(fn)(*args)
    return TL.check_jaxpr_rules("fixture", jaxpr.jaxpr)


def _rules(findings):
    return sorted(f.rule for f in findings)


# ---------------------------------------------------------------------------
# Golden fixtures: each TRC rule fires on its planted violation
# ---------------------------------------------------------------------------

def test_trc001_host_callbacks_fire(bad):
    import jax.numpy as jnp

    fs = _trace_rules(bad.leaky_callback, jnp.int32(1))
    assert _rules(fs) == ["TRC001", "TRC001"], fs
    assert any("pure_callback" in f.message for f in fs)
    assert any("debug_callback" in f.message for f in fs)


def test_trc001_recurses_into_scan_bodies(bad):
    import jax.numpy as jnp

    fs = _trace_rules(bad.callback_in_scan, jnp.int32(0))
    assert _rules(fs) == ["TRC001"], fs


def test_trc002_unstable_sort_fires(bad):
    import jax.numpy as jnp

    fs = _trace_rules(bad.unstable_sort, jnp.arange(8, dtype=jnp.int32))
    assert _rules(fs) == ["TRC002"], fs
    assert "is_stable" in fs[0].message


def test_trc002_float_scatter_accum_fires_int_stays_clean(bad):
    import jax.numpy as jnp

    idx = jnp.zeros((4,), jnp.int32)  # every row hits index 0: duplicates
    fs = _trace_rules(bad.float_scatter_accum,
                      jnp.zeros((8,), jnp.float32), idx,
                      jnp.ones((4,), jnp.float32))
    assert _rules(fs) == ["TRC002"], fs
    fs = _trace_rules(bad.int_scatter_accum,
                      jnp.zeros((8,), jnp.int32), idx,
                      jnp.ones((4,), jnp.int32))
    assert fs == [], fs


def _x64_findings(fn, *args):
    built = TL.Built(fn=fn, args=args)
    prog = TL.TraceProgram("fixture", "fixture", lambda: built)
    return TL.check_x64_invariance("fixture", prog, built)


def test_trc003_unpinned_sum_changes_output_dtype(bad):
    import jax.numpy as jnp

    fs = _x64_findings(bad.x64_leaky_sum, jnp.ones((8,), bool))
    assert "TRC003" in _rules(fs), fs
    assert any("output dtypes change" in f.message for f in fs)


def test_trc003_f64_intermediate_flagged(bad):
    import jax.numpy as jnp

    with warnings.catch_warnings():
        # Without x64 the f64 cast truncates with a UserWarning — that
        # silent truncation is exactly what the rule exists to expose.
        warnings.simplefilter("ignore")
        fs = _x64_findings(bad.f64_intermediate, jnp.ones((4,), jnp.float32))
    assert any(f.rule == "TRC003" and "float64" in f.message
               for f in fs), fs


def test_clean_program_has_no_findings(bad):
    import jax.numpy as jnp

    x = jnp.arange(8, dtype=jnp.int32)
    assert _trace_rules(bad.clean_program, x) == []
    assert _x64_findings(bad.clean_program, x) == []


# ---------------------------------------------------------------------------
# DET008/DET009 — hot-loop sync discipline (AST pass)
# ---------------------------------------------------------------------------

def test_hot_sync_fixture_golden_counts():
    src = open(os.path.join(FIXTURES, "hot_sync.py")).read()
    fs = scan_source(src, "hot_sync.py")  # marker auto-enables the pass
    counts = {}
    for f in fs:
        counts[f.rule] = counts.get(f.rule, 0) + 1
    assert counts == {"DET008": 3, "DET009": 1}, \
        "\n".join(f.render() for f in fs)


def test_hot_pass_off_for_unmarked_modules():
    src = ("import jax\n"
           "x = jax.device_get(1)\n")
    assert scan_source(src, "cold_module.py") == []
    assert [f.rule for f in scan_source(src, "cold.py", hot=True)] \
        == ["DET008"]


def test_repo_hot_modules_are_in_the_pass_and_clean():
    """The three orchestration modules run the sync pass by path and are
    clean modulo their reason= pragmas — i.e. the counted-fetch contract
    the runtime tests enforce dynamically holds statically too."""
    from madsim_tpu.analysis.rules import HOT_LOOP_MODULES

    assert "madsim_tpu/parallel/sweep.py" in HOT_LOOP_MODULES
    # The bridge pool's parent round loop lives by the same counted-fetch
    # contract (bridge/pool.py `_fetch` seam; PR 15) — keep it in the
    # pass by path, and marker-opted-in at its first line too.
    assert "madsim_tpu/bridge/pool.py" in HOT_LOOP_MODULES
    from madsim_tpu.analysis.escape import is_hot_loop_module

    src = open(os.path.join(REPO, "madsim_tpu/bridge/pool.py")).read()
    assert is_hot_loop_module("anywhere/pool.py", src)  # marker opt-in
    for rel in sorted(HOT_LOOP_MODULES):
        src = open(os.path.join(REPO, rel)).read()
        fs = scan_source(src, rel)
        assert fs == [], "\n".join(f.render() for f in fs)


def test_det008_pragma_requires_reason():
    src = ("# tracelint: hot-loop\n"
           "import jax\n"
           "_fetch = jax.device_get  # detlint: allow[DET008]\n")
    (f,) = scan_source(src, "hot.py")
    assert f.rule == "DET900" and "reason=" in f.message
    src = src.replace("allow[DET008]", "allow[DET008] reason=test hook")
    assert scan_source(src, "hot.py") == []


def test_taint_clears_through_fetch():
    src = ("# tracelint: hot-loop\n"
           "import jax.numpy as jnp\n"
           "def f(_fetch, x):\n"
           "    y = jnp.sum(x)\n"
           "    y = _fetch(y)\n"
           "    return int(y)\n")
    assert scan_source(src, "hot.py") == []


# ---------------------------------------------------------------------------
# DET901 — stale allowlist entries
# ---------------------------------------------------------------------------

def test_stale_allowlist_entry_flagged(tmp_path):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "clean.py").write_text("x = 1\n")
    (pkg / "dirty.py").write_text("import time\nt = time.time()\n")
    allow = Allowlist.parse("pkg/dirty.py:DET001\n"
                            "pkg/ghost.py:DET002\n"        # stale
                            "elsewhere/unscanned.py\n")    # not covered
    fs = run_lint(str(tmp_path), ["pkg"], allow)
    assert [f.rule for f in fs] == ["DET901"]
    assert "ghost.py" in fs[0].message and fs[0].line == 2


def test_repo_allowlist_has_no_stale_entries():
    allow = Allowlist.load(os.path.join(REPO, "detlint-allow.txt"))
    fs = run_lint(REPO, ["madsim_tpu", "tools"], allow)
    assert [f for f in fs if f.rule == "DET901"] == [], \
        "\n".join(f.render() for f in fs)


# ---------------------------------------------------------------------------
# TRC004 — the donation-drop mutation is caught
# ---------------------------------------------------------------------------

def _scratch_ledger(alias_min):
    return {"schema": B.LEDGER_SCHEMA, "justification": "test",
            "programs": {"engine.scratch": {
                "alias_fraction": {"measured": 1.0, "min": alias_min}}}}


def test_donation_drop_mutation_is_caught():
    """A scratch copy of the run entry point with its donation
    declaration broken (plain jit, no donate_argnums) must trip TRC004
    against the recorded alias floor; the intact entry point must not.
    Both compile FRESH — a cache-deserialized executable reads alias 0
    and would flag the healthy program too."""
    import jax

    eng = TL._bug_engine()
    state = eng.init(np.arange(8))
    intact = B.measure_compiled(
        B.compile_fresh(eng._run.lower(state, 50)))
    broken_fn = jax.jit(eng._run_impl, static_argnums=1)  # donation dropped
    broken = B.measure_compiled(
        B.compile_fresh(broken_fn.lower(state, 50)))

    ledger = _scratch_ledger(alias_min=0.995)
    ok = B.diff_ledger({"engine.scratch": intact}, ledger,
                       donates={"engine.scratch": True})
    assert ok == [], ok
    bad = B.diff_ledger({"engine.scratch": broken}, ledger,
                        donates={"engine.scratch": True})
    assert [f.rule for f in bad] == ["TRC004"], bad
    assert broken["alias_fraction"] < 0.01  # the drop really is total
    assert intact["alias_fraction"] > 0.999


# ---------------------------------------------------------------------------
# The budget ledger gates
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def engine_run_measured():
    """ONE fresh compile of the ledger's engine.run program, shared by
    the budget-gate tests below (fresh compiles are the expensive part
    of this file)."""
    prog = TL.registry()["engine.run"]
    return TL.measure_program("engine.run", prog)


def test_ledger_passes_on_current_program(engine_run_measured):
    ledger = B.load_ledger()
    fs = B.diff_ledger({"engine.run": engine_run_measured}, ledger,
                       donates={"engine.run": True})
    assert fs == [], "\n".join(f.render() for f in fs)


def test_tampered_ledger_fails_budget_gate(engine_run_measured):
    """`make lint` must fail when a hot program's flops exceed the
    ledger: tighten the checked-in budget below the fresh measurement
    and the diff must report BUD001 (same code path the CLI gates on)."""
    ledger = json.loads(json.dumps(B.load_ledger()))  # deep copy
    entry = ledger["programs"]["engine.run"]
    entry["flops_per_world"]["budget"] = \
        engine_run_measured["flops_per_world"] * 0.5
    entry["temp_bytes"]["budget"] = 1
    fs = B.diff_ledger({"engine.run": engine_run_measured}, ledger,
                       donates={"engine.run": True})
    assert sorted(f.rule for f in fs) == ["BUD001", "BUD001"], fs
    assert all("budget" in f.message for f in fs)


def test_ledger_and_registry_agree():
    """BUD002 structure contract: the checked-in ledger covers exactly
    the budget-tracked programs (so `trace` can never silently skip a
    hot program), and drift in either direction is a finding."""
    ledger = B.load_ledger()
    reg = TL.registry()
    budget_progs = {k for k, p in reg.items() if p.budget}
    assert set(ledger["programs"]) == budget_progs
    # A measured program missing from the ledger:
    fs = B.diff_ledger({"new.prog": {"flops": 1.0}},
                       {"schema": B.LEDGER_SCHEMA, "programs": {}})
    assert [f.rule for f in fs] == ["BUD002"]
    # A ledger entry no registered program backs:
    fs = B.diff_ledger({}, ledger, registered=["engine.run"])
    assert fs and all(f.rule == "BUD002" for f in fs)


def test_budget_ratchet_and_rebase():
    """Regeneration keeps a still-fitting ceiling (no churn on
    improvement) and re-bases with headroom only when exceeded."""
    prev = {"flops": {"measured": 100.0, "budget": 120.0}}
    kept = B.make_entry({"flops": 90.0, "alias_fraction": 1.0},
                        "n", prev)
    assert kept["flops"]["budget"] == 120.0
    moved = B.make_entry({"flops": 200.0, "alias_fraction": 1.0},
                         "n", prev)
    assert moved["flops"]["budget"] == float(int(200.0 * B.HEADROOM + 1))


# ---------------------------------------------------------------------------
# The tier-1 self-scan: the repo's own programs are clean
# ---------------------------------------------------------------------------

def test_self_scan_trace_rules_clean():
    """Every registered hot-path program — engine run/push_many, both
    superstep variants, the coverage folds, compactor, refill select,
    bridge step/drain — passes TRC001-003 with zero findings. Trace-only
    (no XLA compiles): the budget/donation leg runs in `make tracelint`
    where its fresh-compile cost belongs."""
    findings, measured = TL.run_trace(budget_check=False)
    assert findings == [], "\n".join(f.render() for f in findings)
    assert measured == {}


def test_registry_covers_the_hot_paths():
    names = set(TL.registry())
    for required in ("engine.run", "engine.pallas_step", "engine.push_many",
                     "engine.refill_select", "sweep.superstep",
                     "sweep.superstep_min_one", "sweep.superstep_coverage",
                     "sweep.coverage_endfold", "sweep.compactor",
                     "bridge.step", "bridge.drain"):
        assert required in names, f"{required} missing from the registry"


# ---------------------------------------------------------------------------
# CLI surface
# ---------------------------------------------------------------------------

def test_trace_cli_list_programs(capsys):
    assert main_trace(["--list-programs"]) == 0
    out = capsys.readouterr().out
    assert "engine.run" in out and "bridge.step" in out
    assert "[budget,donates]" in out


def test_trace_cli_unknown_program_is_usage_error(capsys):
    assert main_trace(["--programs", "no.such.prog", "--no-budgets"]) == 2


def test_trace_cli_single_program_json(capsys):
    rc = main_trace(["--programs", "engine.push_many", "--no-budgets",
                     "--json"])
    assert rc == 0
    assert json.loads(capsys.readouterr().out) == []


def test_github_format_annotations(capsys):
    rc = detlint_main(["--root", os.path.join(REPO, "tests", "fixtures",
                                              "detlint"),
                       "--no-parity", "--format=github", "bad_socket.py"])
    assert rc == 1
    out = capsys.readouterr().out
    assert "::error file=bad_socket.py,line=" in out
    assert "title=DET005" in out
