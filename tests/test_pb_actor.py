"""Primary-backup device actor: the DeviceEngine protocol's second family."""
import numpy as np

from madsim_tpu.engine import (
    DeviceEngine, EngineConfig, FAULT_KILL, FAULT_RESTART,
)
from madsim_tpu.engine.pb_actor import PBActor, PBDeviceConfig

PCFG = PBDeviceConfig(n=3, n_writes=4)
ECFG = EngineConfig(n_nodes=3, outbox_cap=4, queue_cap=48,
                    t_limit_us=2_000_000)


def test_pb_commits_all_writes_clean():
    eng = DeviceEngine(PBActor(PCFG), ECFG)
    obs = eng.observe(eng.run(eng.init(np.arange(32)), 4000))
    assert not obs["bug"].any()
    assert not obs["overflow"].any()
    assert (obs["committed_max"] == PCFG.n_writes).all()
    assert (obs["min_commit"] >= 1).all()  # commits propagated to backups


def test_pb_failover_preserves_committed_writes():
    # Kill the initial primary after the first writes commit; a backup
    # takes over. Durability invariant must hold in every world.
    eng = DeviceEngine(PBActor(PCFG), ECFG)
    faults = np.array([[420_000, FAULT_KILL, 0, 0],
                       [1_500_000, FAULT_RESTART, 0, 0]], np.int32)
    obs = eng.observe(eng.run(eng.init(np.arange(64), faults=faults), 8000))
    assert not obs["bug"].any()
    assert (obs["views_changed"] >= 1).all(), "failover must have happened"
    assert (obs["committed_max"] >= 1).all(), "pre-kill writes committed"


def test_pb_early_commit_bug_is_found_by_sweep():
    # buggy_commit_early commits after ONE ack. Under packet loss, the
    # replicate to the second backup can be dropped while the first ack
    # commits; killing the primary then strands the committed write, and
    # the backup that never saw it can win the failover — the durability
    # checker flags it, on some seeds.
    lossy = EngineConfig(n_nodes=3, outbox_cap=4, queue_cap=48,
                         t_limit_us=2_000_000, loss_rate=0.3)
    pcfg = PBDeviceConfig(n=3, n_writes=4, buggy_commit_early=True)
    eng = DeviceEngine(PBActor(pcfg), lossy)
    faults = np.array([[130_000, FAULT_KILL, 0, 0]], np.int32)
    obs = eng.observe(eng.run(eng.init(np.arange(256), faults=faults), 8000))
    assert obs["bug"].any(), "the seed sweep must catch the lost write"
    assert not obs["bug"].all(), "only some interleavings lose the write"
    # The same loss + schedule with the CORRECT all-ack protocol never
    # trips the checker: an unreplicated entry simply never commits.
    good = DeviceEngine(PBActor(PCFG), lossy)
    obs2 = good.observe(good.run(good.init(np.arange(256), faults=faults),
                                 8000))
    assert not obs2["bug"].any()


def test_pb_deterministic_and_traceable():
    import jax

    eng = DeviceEngine(PBActor(PCFG), ECFG)
    a = eng.run(eng.init(np.arange(8)), 4000)
    b = eng.run(eng.init(np.arange(8)), 4000)
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        assert np.array_equal(np.asarray(x), np.asarray(y))
    trace = eng.trace(3, max_steps=4000)
    kinds = {e["kind"] for e in trace}
    assert "Write" in kinds and "Replicate" in kinds and "Ack" in kinds
    times = [e["t_us"] for e in trace]
    assert times == sorted(times)


def test_pb_out_of_order_acks_commit_full_prefix():
    # Ack loss can make a later entry reach quorum before an earlier one
    # (retransmitted via nothing — the earlier slot completes when its
    # last ack lands). Jumped commits must record the WHOLE prefix, or the
    # durability checker would flag the CORRECT protocol on clean runs.
    lossy = EngineConfig(n_nodes=3, outbox_cap=4, queue_cap=48,
                         t_limit_us=2_000_000, loss_rate=0.15)
    eng = DeviceEngine(PBActor(PCFG), lossy)
    obs = eng.observe(eng.run(eng.init(np.arange(512)), 8000))
    assert not obs["bug"].any(), "correct protocol must never be flagged"
