"""gRPC codegen (.proto on-ramp): one generated file, two transports.

madsim-tonic-build parity (`madsim-tonic-build/src/{client,server}.rs`): a
``.proto`` service definition compiles — via the system protoc + this
repo's stub generator — into code that runs BOTH on real grpcio
(production transport, no simulation) and inside the simulated network
under ``grpc_aio.patched()``, unchanged.
"""
import shutil
import sys

import pytest

import madsim_tpu as ms
from madsim_tpu import time as mtime
from madsim_tpu.shims import grpc_aio
from madsim_tpu.tools.protogen import compile_protos

grpc = pytest.importorskip("grpc")
pytest.importorskip("google.protobuf")
if shutil.which("protoc") is None:
    # protogen shells out to the system protoc; absent compiler is an
    # environment gap, not a codegen failure.
    pytest.skip("system protoc not installed", allow_module_level=True)

PROTO = """
syntax = "proto3";
package helloworld;

message HelloRequest { string name = 1; int32 id = 2; }
message HelloReply { string message = 1; }

service Greeter {
  rpc SayHello (HelloRequest) returns (HelloReply);
  rpc LotsOfReplies (HelloRequest) returns (stream HelloReply);
  rpc LotsOfGreetings (stream HelloRequest) returns (HelloReply);
  rpc BidiHello (stream HelloRequest) returns (stream HelloReply);
}
"""


@pytest.fixture(scope="module")
def gen(tmp_path_factory):
    out = tmp_path_factory.mktemp("protogen")
    proto = out / "greeter.proto"
    proto.write_text(PROTO)
    paths = compile_protos([str(proto)], str(out))
    assert any(p.endswith("greeter_pb2.py") for p in paths)
    assert any(p.endswith("greeter_pb2_grpc.py") for p in paths)
    sys.path.insert(0, str(out))
    try:
        import greeter_pb2
        import greeter_pb2_grpc

        yield greeter_pb2, greeter_pb2_grpc
    finally:
        sys.path.remove(str(out))
        sys.modules.pop("greeter_pb2", None)
        sys.modules.pop("greeter_pb2_grpc", None)


def _make_servicer(pb2, grpc_mod):
    class Greeter(grpc_mod.GreeterServicer):
        async def SayHello(self, request, context):
            return pb2.HelloReply(message=f"Hello, {request.name}!")

        async def LotsOfReplies(self, request, context):
            for i in range(3):
                yield pb2.HelloReply(message=f"{request.name}-{i}")

        async def LotsOfGreetings(self, request_iterator, context):
            names = [r.name async for r in request_iterator]
            return pb2.HelloReply(message=",".join(names))

        async def BidiHello(self, request_iterator, context):
            async for r in request_iterator:
                yield pb2.HelloReply(message=f"hi {r.name}")

    return Greeter()


async def _drive(pb2, stub):
    """Exercise all four streaming modes through a generated stub."""
    r = await stub.SayHello(pb2.HelloRequest(name="world", id=7))
    assert r.message == "Hello, world!"
    streamed = [x.message async for x in
                stub.LotsOfReplies(pb2.HelloRequest(name="s"))]
    assert streamed == ["s-0", "s-1", "s-2"]

    async def reqs():
        for n in ("a", "b", "c"):
            yield pb2.HelloRequest(name=n)

    r = await stub.LotsOfGreetings(reqs())
    assert r.message == "a,b,c"
    bidi = [x.message async for x in stub.BidiHello(reqs())]
    assert bidi == ["hi a", "hi b", "hi c"]
    return True


def test_generated_code_runs_in_sim(gen):
    pb2, pb2_grpc = gen
    rt = ms.Runtime(seed=3)
    rt.set_time_limit(300)

    async def main():
        h = ms.Handle.current()

        async def serve():
            server = grpc.aio.server()
            pb2_grpc.add_GreeterServicer_to_server(
                _make_servicer(pb2, pb2_grpc), server)
            server.add_insecure_port("10.0.0.1:50051")
            await server.start()
            await server.wait_for_termination()

        h.create_node(name="server", ip="10.0.0.1", init=serve)
        cli = h.create_node(name="cli", ip="10.0.0.2")
        done = ms.sync.SimFuture()

        async def client():
            while True:
                try:
                    async with grpc.aio.insecure_channel("10.0.0.1:50051") as ch:
                        stub = pb2_grpc.GreeterStub(ch)
                        done.set_result(await _drive(pb2, stub))
                        return
                except grpc.RpcError:
                    await mtime.sleep(0.05)  # server bind race: retry

        cli.spawn(client())
        return await done

    with grpc_aio.patched():
        assert rt.block_on(main())


def test_generated_code_runs_on_real_grpcio(gen):
    # The SAME generated file against the real grpcio transport (no sim) —
    # the `pub use tonic::*` half of the dual-transport contract.
    import asyncio

    pb2, pb2_grpc = gen

    async def main():
        server = grpc.aio.server()
        pb2_grpc.add_GreeterServicer_to_server(
            _make_servicer(pb2, pb2_grpc), server)
        port = server.add_insecure_port("127.0.0.1:0")
        await server.start()
        try:
            async with grpc.aio.insecure_channel(f"127.0.0.1:{port}") as ch:
                stub = pb2_grpc.GreeterStub(ch)
                return await _drive(pb2, stub)
        finally:
            await server.stop(None)

    assert asyncio.run(main())


def test_unoverridden_servicer_method_is_unimplemented(gen):
    # The generated Servicer base must surface UNIMPLEMENTED (the
    # grpc_python_plugin contract), not INTERNAL/UNKNOWN.
    pb2, pb2_grpc = gen
    rt = ms.Runtime(seed=8)

    async def main():
        server = grpc.aio.server()
        # Register the BASE servicer: nothing overridden.
        pb2_grpc.add_GreeterServicer_to_server(pb2_grpc.GreeterServicer(),
                                               server)
        server.add_insecure_port("127.0.0.1:50051")
        await server.start()
        ch = grpc.aio.insecure_channel("127.0.0.1:50051")
        stub = pb2_grpc.GreeterStub(ch)
        with pytest.raises(grpc.RpcError) as ei:
            await stub.SayHello(pb2.HelloRequest(name="x"))
        assert ei.value.code() == grpc.StatusCode.UNIMPLEMENTED
        # Streaming methods too: an unoverridden async-coroutine base must
        # surface UNIMPLEMENTED, not a TypeError-induced INTERNAL.
        with pytest.raises(grpc.RpcError) as ei:
            async for _ in stub.LotsOfReplies(pb2.HelloRequest(name="x")):
                pass
        assert ei.value.code() == grpc.StatusCode.UNIMPLEMENTED
        await ch.close()
        await server.stop()

    with grpc_aio.patched():
        rt.block_on(main())


def test_generated_code_is_deterministic_in_sim(gen):
    pb2, pb2_grpc = gen

    def world(seed):
        rt = ms.Runtime(seed=seed)
        trace = []

        async def main():
            server = grpc.aio.server()
            pb2_grpc.add_GreeterServicer_to_server(
                _make_servicer(pb2, pb2_grpc), server)
            server.add_insecure_port("127.0.0.1:50051")
            await server.start()
            ch = grpc.aio.insecure_channel("127.0.0.1:50051")
            stub = pb2_grpc.GreeterStub(ch)
            for i in range(5):
                r = await stub.SayHello(pb2.HelloRequest(name=f"n{i}"))
                trace.append((round(mtime.monotonic(), 9), r.message))
            await ch.close()
            await server.stop()

        with grpc_aio.patched():
            rt.block_on(main())
        return trace

    a, b, c = world(11), world(11), world(12)
    assert a == b and len(a) == 5
    assert a != c
