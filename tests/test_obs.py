"""Observability subsystem (PR "Flight recorder"): device-resident
metrics, timeline export, and repro bundles (docs/observability.md).

The load-bearing contract under test is **bitwise invisibility**:
``EngineConfig(metrics=True)`` rides a write-only pytree leaf alongside
``WorldState``, so a metrics-on sweep walks bit-identical trajectories
to metrics-off — for every actor family, across the plain, recycled and
pipelined orchestration modes — while metrics-off compiles the exact
pre-metrics program (the op budget in tests/test_queue_insert.py is the
other half of that gate).
"""
import dataclasses
import json
import os

import numpy as np
import pytest

from madsim_tpu.engine import (
    DeviceEngine,
    EngineConfig,
    FAULT_KILL,
    FAULT_RESTART,
    FAULT_RESUME,
    PBActor,
    PBDeviceConfig,
    RaftActor,
    RaftDeviceConfig,
    TPCActor,
    TPCDeviceConfig,
)
from madsim_tpu.obs import (
    NUM_FAULT_KINDS,
    MetricsBlock,
    render_text,
    trace_to_chrome,
)
from madsim_tpu.obs.bundle import (
    load_bundle,
    write_sweep_bundle,
    write_test_bundle,
)
from madsim_tpu.obs.cli import main as obs_main
from madsim_tpu.parallel.sweep import sweep

FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "fixtures")

RAFT_FAULTS = np.array([[300_000, FAULT_KILL, 0, 0],
                        [700_000, FAULT_RESTART, 0, 0]], np.int32)

_FAMILIES = {
    "raft": (lambda: RaftActor(RaftDeviceConfig(n=3, n_proposals=2,
                                                buggy_double_vote=True)),
             EngineConfig(n_nodes=3, outbox_cap=4, queue_cap=64,
                          t_limit_us=1_500_000),
             RAFT_FAULTS),
    "pb": (lambda: PBActor(PBDeviceConfig(n=3, n_writes=4)),
           EngineConfig(n_nodes=3, outbox_cap=4, queue_cap=64,
                        t_limit_us=1_200_000, loss_rate=0.05),
           None),
    "tpc": (lambda: TPCActor(TPCDeviceConfig(n=4, n_txns=4,
                                             buggy_presumed_commit=True)),
            EngineConfig(n_nodes=4, outbox_cap=5, queue_cap=64,
                         t_limit_us=1_200_000, loss_rate=0.1),
            None),
}

_MODES = {
    "plain": dict(pipeline=False),
    "recycled": dict(recycle=True, batch_worlds=16, pipeline=True),
    "pipelined": dict(pipeline=True),
}


@pytest.fixture(scope="module")
def engines():
    """One metrics-off + one metrics-on engine per family, shared across
    the mode matrix (engine builds dominate this module's runtime)."""
    out = {}
    for name, (make_actor, cfg, faults) in _FAMILIES.items():
        out[name] = (
            DeviceEngine(make_actor(), cfg),
            DeviceEngine(make_actor(),
                         dataclasses.replace(cfg, metrics=True)),
            faults,
        )
    return out


def test_fault_hist_width_matches_engine_op_range():
    # obs/metrics.py must not import the engine (the engine imports it),
    # so the histogram width is pinned by this assertion instead.
    assert NUM_FAULT_KINDS == FAULT_RESUME + 1


# ---------------------------------------------------------------------------
# Tier-1: bitwise invisibility across families x orchestration modes
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("family", sorted(_FAMILIES))
@pytest.mark.parametrize("mode", sorted(_MODES))
def test_metrics_on_sweep_bitwise_identical(engines, family, mode):
    eng_off, eng_on, faults = engines[family]
    seeds = np.arange(40)
    kw = dict(chunk_steps=64, max_steps=3_000, faults=faults,
              **_MODES[mode])
    res_off = sweep(None, eng_off.cfg, seeds, engine=eng_off, **kw)
    res_on = sweep(None, eng_on.cfg, seeds, engine=eng_on, **kw)
    # Every non-metrics observation bitwise equal, same occupancy story.
    assert not any(k.startswith("m_") for k in res_off.observations)
    for k, v in res_off.observations.items():
        np.testing.assert_array_equal(v, res_on.observations[k], err_msg=k)
    np.testing.assert_array_equal(res_off.n_active_history,
                                  res_on.n_active_history)
    assert res_off.failing_seeds == res_on.failing_seeds
    assert res_off.steps_run == res_on.steps_run
    # The metrics frames exist, attribute per seed, and are consistent
    # with the engine's own counters.
    assert res_off.metrics is None
    m = res_on.metrics
    assert set(m["per_seed"]) == set(MetricsBlock._fields)
    obs = res_on.observations
    ps = m["per_seed"]
    np.testing.assert_array_equal(
        ps["msgs_delivered"] + ps["timer_fires"], obs["delivered"])
    np.testing.assert_array_equal(
        ps["drop_stale"] + ps["drop_dead"], obs["dropped"])
    np.testing.assert_array_equal(ps["vtime_us"], obs["now_us"])
    np.testing.assert_array_equal(ps["kind_hist"].sum(axis=1),
                                  obs["delivered"])
    if faults is not None:
        # Any world whose clock passed a fault row's time popped that
        # row first (earliest-first pop order): its histogram bin is 1.
        # Worlds frozen earlier (stop_on_bug) legitimately show 0.
        past_kill = obs["now_us"] > 300_000
        past_restart = obs["now_us"] > 700_000
        assert (ps["fault_hist"][past_kill, FAULT_KILL] == 1).all()
        assert (ps["fault_hist"][past_restart, FAULT_RESTART] == 1).all()
        assert (ps["fault_hist"] <= 1).all()
    # The aggregate frame is plain JSON (the bench sim_metrics contract).
    # (No msgs_sent >= msgs_delivered identity: init-scheduled events —
    # proposals, writes — deliver as messages without a send.)
    json.dumps(m["aggregate"])
    agg = m["aggregate"]
    assert agg["msgs_sent"] > 0 and agg["timer_fires"] > 0
    assert agg["drop_loss"] <= agg["msgs_sent"]


@pytest.fixture(scope="module")
def wide_engines():
    """The int32 reference profile (EngineConfig(packed=False)) per
    family — the crosscheck twin of the packed-by-default ``engines``
    fixture (PR "Roofline round 2"; the sequential_insert pattern
    applied to lane dtypes)."""
    out = {}
    for name, (make_actor, cfg, faults) in _FAMILIES.items():
        out[name] = (DeviceEngine(make_actor(),
                                  dataclasses.replace(cfg, packed=False)),
                     faults)
    return out


@pytest.mark.parametrize("family", sorted(_FAMILIES))
@pytest.mark.parametrize("mode", sorted(_MODES))
def test_packed_sweep_bitwise_identical_to_wide(engines, wide_engines,
                                                family, mode):
    """Packed lane dtypes are trajectory-invisible: a packed sweep (the
    default profile — i8/i16 node/code/slot/payload lanes) walks
    bit-identical trajectories to the int32 reference profile, for
    every actor family across the plain/recycled/pipelined orchestration
    modes. Only the at-rest dtypes differ; every observed value, the
    occupancy story, and the failing-seed set must match exactly."""
    eng_packed, _on, faults = engines[family]
    eng_wide, _ = wide_engines[family]
    assert eng_packed.cfg.packed and not eng_wide.cfg.packed
    seeds = np.arange(40)
    kw = dict(chunk_steps=64, max_steps=3_000, faults=faults,
              **_MODES[mode])
    res_p = sweep(None, eng_packed.cfg, seeds, engine=eng_packed, **kw)
    res_w = sweep(None, eng_wide.cfg, seeds, engine=eng_wide, **kw)
    assert set(res_p.observations) == set(res_w.observations)
    for k, v in res_w.observations.items():
        np.testing.assert_array_equal(np.asarray(res_p.observations[k]),
                                      np.asarray(v), err_msg=k)
    np.testing.assert_array_equal(res_p.n_active_history,
                                  res_w.n_active_history)
    assert res_p.failing_seeds == res_w.failing_seeds
    assert res_p.steps_run == res_w.steps_run


def test_metrics_survive_checkpoint_resume(engines, tmp_path):
    """The extra leaf rides the checkpoint format unchanged: a resumed
    metrics-on sweep equals the unbroken run — every MetricsBlock
    counter bit-identical per seed, and the coverage ledger's
    fold-order-invariant halves (hits, first_seen) too. The interrupted
    run retires worlds BEFORE the checkpoint; the resumed call folds
    them through its resume pre-pass (parallel/sweep.py), so ledger
    identity is the property actually under test."""
    _off, eng_on, faults = engines["raft"]
    seeds = np.arange(24)
    full = sweep(None, eng_on.cfg, seeds, engine=eng_on, chunk_steps=128,
                 max_steps=3_000, faults=faults)
    path = str(tmp_path / "m.npz")
    interrupted = sweep(None, eng_on.cfg, seeds, engine=eng_on,
                        chunk_steps=128, max_steps=256, faults=faults,
                        checkpoint_path=path, checkpoint_every_chunks=1)
    resumed = sweep(None, eng_on.cfg, seeds, engine=eng_on, chunk_steps=128,
                    max_steps=3_000, faults=faults, checkpoint_path=path,
                    resume=True)
    for k, v in full.observations.items():
        np.testing.assert_array_equal(v, resumed.observations[k], err_msg=k)
    # Explicitly: the per-seed MetricsBlock frames, counter for counter.
    mf, mr = full.metrics["per_seed"], resumed.metrics["per_seed"]
    assert set(mf) == set(MetricsBlock._fields)
    for k in mf:
        np.testing.assert_array_equal(mf[k], mr[k], err_msg=f"m_{k}")
    # Coverage ledger: hits and first_seen are counts/minima over the
    # folded set, so the resumed run's ledger equals the unbroken one's
    # bit for bit (novelty_curve is per-call history by design).
    cf, cr = full.coverage, resumed.coverage
    assert cf is not None and cr is not None
    np.testing.assert_array_equal(cf.hits, cr.hits)
    np.testing.assert_array_equal(cf.first_seen_seed, cr.first_seen_seed)
    assert cf.distinct_behaviors == cr.distinct_behaviors
    # Sanity that the scenario is non-trivial: some worlds really did
    # retire before the checkpoint cut.
    assert interrupted.n_active_history.size >= 1


# ---------------------------------------------------------------------------
# Tier-1: the behavior-coverage ledger (obs/coverage.py)
# ---------------------------------------------------------------------------

def test_coverage_novelty_curve_contract(engines):
    """SweepResult.coverage acceptance axes: the novelty curve is
    monotone non-decreasing, rides the n_active_history cadence, and is
    bit-deterministic across pipeline on/off; every real seed folds into
    the ledger exactly once (hits sum == n), with first-seen-seed
    attribution consistent with occupancy."""
    _off, eng_on, faults = engines["raft"]
    seeds = np.arange(40)
    kw = dict(chunk_steps=64, max_steps=3_000, faults=faults)
    pip = sweep(None, eng_on.cfg, seeds, engine=eng_on, pipeline=True, **kw)
    ser = sweep(None, eng_on.cfg, seeds, engine=eng_on, pipeline=False, **kw)
    rec = sweep(None, eng_on.cfg, seeds, engine=eng_on, pipeline=True,
                recycle=True, batch_worlds=16, **kw)
    for res in (pip, ser, rec):
        cov = res.coverage
        assert cov is not None
        curve = cov.novelty_curve
        assert curve.shape == res.n_active_history.shape
        assert (np.diff(curve) >= 0).all()
        assert cov.distinct_behaviors >= int(curve[-1])
        assert int(cov.hits.sum()) == len(seeds)  # each seed folded once
        # Bucket attribution: empty buckets carry -1, hit buckets a real
        # seed id (the LOWEST folded in — fold-order invariant).
        fs = cov.first_seen_seed
        assert ((fs == -1) == (cov.hits == 0)).all()
        assert fs[fs >= 0].max(initial=0) < len(seeds)
        assert (np.asarray(cov.new_behaviors_per_chunk).sum()
                == int(curve[-1]) if curve.size else True)
    # Deterministic across orchestration modes: same folded set, same
    # ledger — pipelined == serial == recycled, curve included for the
    # two same-cadence loops.
    np.testing.assert_array_equal(pip.coverage.novelty_curve,
                                  ser.coverage.novelty_curve)
    for a, b in ((pip, ser), (pip, rec)):
        np.testing.assert_array_equal(a.coverage.hits, b.coverage.hits)
        np.testing.assert_array_equal(a.coverage.first_seen_seed,
                                      b.coverage.first_seen_seed)
    # And under an early stop, where the pipelined loop's in-flight
    # superstep must be a ledger pass-through (zero chunks → zero folds)
    # and truncated still-live worlds fold at exit in BOTH loops.
    stop_kw = dict(chunk_steps=64, max_steps=3_000, faults=faults,
                   stop_on_first_bug=True)
    sp = sweep(None, eng_on.cfg, seeds, engine=eng_on, pipeline=True,
               **stop_kw)
    ss = sweep(None, eng_on.cfg, seeds, engine=eng_on, pipeline=False,
               **stop_kw)
    np.testing.assert_array_equal(sp.coverage.hits, ss.coverage.hits)
    np.testing.assert_array_equal(sp.coverage.first_seen_seed,
                                  ss.coverage.first_seen_seed)
    np.testing.assert_array_equal(sp.coverage.novelty_curve,
                                  ss.coverage.novelty_curve)
    assert int(sp.coverage.hits.sum()) == len(seeds)


def test_coverage_ledger_matches_on_multihost_mesh(engines):
    """The ledger's mesh reductions (psum for hits, pmin for first-seen)
    span ALL axes of a 2-D DCN×ICI mesh, so the fleet-scale topology
    (ROADMAP item 1) reports the identical ledger."""
    from madsim_tpu.parallel import multihost_mesh

    _off, eng_on, faults = engines["raft"]
    seeds = np.arange(32)
    kw = dict(chunk_steps=64, max_steps=2_048, faults=faults)
    flat = sweep(None, eng_on.cfg, seeds, engine=eng_on, **kw)
    grid = sweep(None, eng_on.cfg, seeds, engine=eng_on,
                 mesh=multihost_mesh(n_hosts=2), **kw)
    np.testing.assert_array_equal(flat.coverage.hits, grid.coverage.hits)
    np.testing.assert_array_equal(flat.coverage.first_seen_seed,
                                  grid.coverage.first_seen_seed)
    np.testing.assert_array_equal(flat.coverage.novelty_curve,
                                  grid.coverage.novelty_curve)


def test_coverage_distinguishes_faulted_sweep(engines):
    """The novelty signal means something: the same seed set under a
    kill/restart schedule exhibits STRICTLY more distinct behaviors than
    the fault-free run (fault histogram + drop causes hash to fresh
    buckets)."""
    _off, eng_on, faults = engines["raft"]
    seeds = np.arange(40)
    faulted = sweep(None, eng_on.cfg, seeds, engine=eng_on, chunk_steps=64,
                    max_steps=3_000, faults=faults)
    clean = sweep(None, eng_on.cfg, seeds, engine=eng_on, chunk_steps=64,
                  max_steps=3_000)
    assert clean.coverage.distinct_behaviors >= 1
    assert (faulted.coverage.distinct_behaviors
            > clean.coverage.distinct_behaviors)


def test_coverage_requires_metrics(engines):
    eng_off, _on, _f = engines["raft"]
    with pytest.raises(ValueError, match="metrics=True"):
        sweep(None, eng_off.cfg, np.arange(8), engine=eng_off,
              chunk_steps=64, max_steps=256, coverage_buckets=64)
    # Metrics-off sweeps simply report no coverage (and compile the
    # unchanged pre-coverage programs — the op-budget gate's other half).
    res = sweep(None, eng_off.cfg, np.arange(8), engine=eng_off,
                chunk_steps=64, max_steps=256)
    assert res.coverage is None


# ---------------------------------------------------------------------------
# Satellite: trace truncation marker
# ---------------------------------------------------------------------------

def test_trace_truncation_marker_and_warning(engines):
    # The clean PB world runs far past 20 steps: the cut must be marked.
    pb_off, _on, _f = engines["pb"]
    with pytest.warns(RuntimeWarning, match="truncated at max_steps"):
        tr = pb_off.trace(0, max_steps=20)
    assert tr[-1]["kind"] == "truncated"
    assert tr[-1]["step"] == 20 and tr[-1]["bug_seen"] is False
    # A completed world gets NO marker: the buggy raft config freezes on
    # the invariant raise well inside the window.
    eng_off, _on, _f = engines["raft"]
    failing = _first_failing_seed(eng_off)
    full = eng_off.trace(failing, max_steps=4_000)
    assert full[-1]["kind"] != "truncated"
    assert any(e.get("bug_raised") for e in full)


def _first_failing_seed(eng) -> int:
    res = sweep(None, eng.cfg, np.arange(128), engine=eng, chunk_steps=64,
                max_steps=4_000)
    assert res.failing_seeds, "buggy config found no failing seed"
    return res.failing_seeds[0]


# ---------------------------------------------------------------------------
# Timeline export
# ---------------------------------------------------------------------------

def test_chrome_trace_ends_at_invariant_raise(engines):
    eng_off, _on, _f = engines["raft"]
    seed = _first_failing_seed(eng_off)
    tr = eng_off.trace(seed, max_steps=4_000)
    doc = trace_to_chrome(tr, seed=seed)
    blob = json.dumps(doc)  # must be valid JSON end to end
    doc2 = json.loads(blob)
    events = doc2["traceEvents"]
    assert events[0]["ph"] == "M"
    body = [e for e in events if e["ph"] == "i"]
    assert len(body) >= len([e for e in tr if e["kind"] != "truncated"])
    assert events[-1]["name"] == "invariant:raise"
    # Timestamps are the virtual-time microseconds, monotone nondecreasing.
    ts = [e["ts"] for e in body]
    assert ts == sorted(ts)
    assert doc2["otherData"]["clock"] == "virtual_us"
    text = render_text(tr)
    assert "INVARIANT VIOLATION" in text
    assert "truncated" not in text


def test_text_renderer_marks_truncation(engines):
    pb_off, _on, _f = engines["pb"]
    with pytest.warns(RuntimeWarning):
        tr = pb_off.trace(1, max_steps=15)
    text = render_text(tr)
    assert "trace truncated" in text and "bug never seen" in text
    doc = trace_to_chrome(tr, seed=1)
    assert doc["traceEvents"][-1]["name"] == "truncated"


def test_polls_to_chrome_host_trace():
    import madsim_tpu as ms
    from madsim_tpu.obs import polls_to_chrome

    rt = ms.Runtime(seed=3)
    rt.task.trace = polls = []

    async def body():
        from madsim_tpu import time as simtime

        await simtime.sleep(0.05)
        return 7

    assert rt.block_on(body()) == 7
    assert polls, "host runtime recorded no polls"
    doc = polls_to_chrome(polls, seed=3)
    body_evs = [e for e in doc["traceEvents"] if e["ph"] == "i"]
    assert len(body_evs) == len(polls)
    assert body_evs[-1]["ts"] == pytest.approx(polls[-1][1] / 1_000.0)


# ---------------------------------------------------------------------------
# Repro bundles + CLI round trips
# ---------------------------------------------------------------------------

def test_device_bundle_round_trips_through_cli(engines, tmp_path, capsys):
    eng_off, _on, _f = engines["raft"]
    seed = _first_failing_seed(eng_off)
    path = write_sweep_bundle(
        str(tmp_path), seed=seed, actor="raft",
        actor_config=eng_off.actor.rcfg, engine_config=eng_off.cfg,
        max_steps=4_000, error="RaftInvariantViolation: double vote")
    bundle = load_bundle(path)
    assert bundle["kind"] == "device_sweep" and bundle["seed"] == seed
    assert bundle["config_hash"]
    out = str(tmp_path / "trace.json")
    rc = obs_main(["replay", "--bundle", path, "--out", out])
    assert rc == 0, capsys.readouterr().err
    with open(out) as f:
        doc = json.load(f)
    assert doc["traceEvents"][-1]["name"] == "invariant:raise"


def test_device_bundle_unreproduced_failure_exits_nonzero(tmp_path):
    # A bundle claiming a failure on a CLEAN config must not silently
    # "reproduce": the CLI exits 1 when the invariant holds.
    path = write_sweep_bundle(
        str(tmp_path), seed=0, actor="raft",
        actor_config=RaftDeviceConfig(n=3),
        engine_config=EngineConfig(n_nodes=3, outbox_cap=4, queue_cap=64,
                                   t_limit_us=200_000),
        max_steps=2_000, error="RaftInvariantViolation: double vote")
    rc = obs_main(["replay", "--bundle", path,
                   "--out", str(tmp_path / "t.json")])
    assert rc == 1


def test_failing_test_writes_bundle_and_cli_reproduces(tmp_path,
                                                       monkeypatch):
    """The acceptance round trip: a failing @test writes a repro bundle
    (MADSIM_REPRO_DIR), and the CLI replays it to the same bug."""
    monkeypatch.syspath_prepend(FIXTURES)
    monkeypatch.setenv("MADSIM_TEST_SEED", "7")
    monkeypatch.setenv("MADSIM_REPRO_DIR", str(tmp_path))
    monkeypatch.delenv("MADSIM_TEST_BACKEND", raising=False)
    import obs_failing_test

    with pytest.raises(RuntimeError, match="obs bundle fixture failure"):
        obs_failing_test.always_fails()
    bundles = [f for f in os.listdir(tmp_path) if f.endswith(".json")]
    assert len(bundles) == 1, bundles
    path = os.path.join(str(tmp_path), bundles[0])
    bundle = load_bundle(path)
    assert bundle["kind"] == "host_test"
    assert bundle["test"] == "obs_failing_test:always_fails"
    assert bundle["env"]["MADSIM_TEST_SEED"] == "7"
    assert bundle["error"].startswith("RuntimeError")
    # Stop the replayed failure from writing bundle-on-bundle into the
    # assertion above's directory.
    monkeypatch.delenv("MADSIM_REPRO_DIR")
    rc = obs_main(["replay", "--bundle", path])
    assert rc == 0


def test_banner_carries_backend_batch_and_fault_digest(capsys,
                                                       monkeypatch):
    import madsim_tpu as ms

    monkeypatch.delenv("MADSIM_REPRO_DIR", raising=False)
    cfg = ms.Config()
    cfg.net.packet_loss_rate = 0.25
    b = ms.Builder(seed=11, backend="bridge", batch=4, config=cfg)
    b._print_banner(11, error=RuntimeError("x"))
    err = capsys.readouterr().err
    assert "MADSIM_TEST_SEED=11" in err
    assert "MADSIM_CONFIG_HASH=" in err
    assert "MADSIM_FAULT_SHA=" in err
    assert "MADSIM_TEST_BACKEND=bridge" in err
    assert "MADSIM_TEST_BATCH=4" in err
    # The fault digest tracks the fault model, not unrelated config.
    import re

    sha = re.search(r"MADSIM_FAULT_SHA=(\w+)", err).group(1)
    b2 = ms.Builder(seed=11)  # default fault model
    b2._print_banner(11)
    sha2 = re.search(r"MADSIM_FAULT_SHA=(\w+)",
                     capsys.readouterr().err).group(1)
    assert sha != sha2


def test_sweep_result_banner_names_fault_schedule(engines):
    eng_off, _on, faults = engines["raft"]
    res = sweep(None, eng_off.cfg, np.arange(64), engine=eng_off,
                chunk_steps=64, max_steps=4_000, faults=faults)
    banner = res.repro_banner()
    assert banner and "fault-schedule sha256:" in banner
    assert res.faults_sha256


# ---------------------------------------------------------------------------
# Bridge: the kernel's metrics block is trajectory-invisible too
# ---------------------------------------------------------------------------

def test_bridge_metrics_block_is_trajectory_invisible():
    from madsim_tpu.bridge.runtime import _sweep_impl

    async def world():
        from madsim_tpu import time as simtime

        for _ in range(4):
            await simtime.sleep(0.01)
        return 99

    seeds = list(range(6))
    plain_outs, plain_traces = _sweep_impl(world, seeds, trace=True)
    profile: dict = {}
    prof_outs, prof_traces = _sweep_impl(world, seeds, trace=True,
                                         profile=profile)
    assert [o.value for o in plain_outs] == [o.value for o in prof_outs]
    assert plain_traces == prof_traces  # bit-identical poll sequences
    sm = profile["sim_metrics"]
    assert sm["timers_set"] >= 4 * len(seeds)
    assert sm["events_fired"] >= 4 * len(seeds)
    assert sm["vtime_ns"] > 0
    assert sm["msgs_sent"] == 0 and sm["msgs_lost"] == 0
    # The per-slot coverage sketch rides the same one-time metrics pull
    # (obs/coverage.py coverage_of_counters over BridgeMetrics).
    cov = profile["coverage"]
    assert cov["worlds_folded"] == len(seeds)
    assert 1 <= cov["distinct_behaviors"] <= len(seeds)
    import json as _json

    _json.dumps(cov)  # plain JSON: the bench sim_metrics sibling record
