"""Observability subsystem (PR "Flight recorder"): device-resident
metrics, timeline export, and repro bundles (docs/observability.md).

The load-bearing contract under test is **bitwise invisibility**:
``EngineConfig(metrics=True)`` rides a write-only pytree leaf alongside
``WorldState``, so a metrics-on sweep walks bit-identical trajectories
to metrics-off — for every actor family, across the plain, recycled and
pipelined orchestration modes — while metrics-off compiles the exact
pre-metrics program (the op budget in tests/test_queue_insert.py is the
other half of that gate).
"""
import dataclasses
import json
import os

import numpy as np
import pytest

from madsim_tpu.engine import (
    DeviceEngine,
    EngineConfig,
    FAULT_KILL,
    FAULT_RESTART,
    FAULT_RESUME,
    PBActor,
    PBDeviceConfig,
    RaftActor,
    RaftDeviceConfig,
    TPCActor,
    TPCDeviceConfig,
)
from madsim_tpu.obs import (
    NUM_FAULT_KINDS,
    MetricsBlock,
    render_text,
    trace_to_chrome,
)
from madsim_tpu.obs.bundle import (
    load_bundle,
    write_sweep_bundle,
    write_test_bundle,
)
from madsim_tpu.obs.cli import main as obs_main
from madsim_tpu.parallel.sweep import sweep

FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "fixtures")

RAFT_FAULTS = np.array([[300_000, FAULT_KILL, 0, 0],
                        [700_000, FAULT_RESTART, 0, 0]], np.int32)

_FAMILIES = {
    "raft": (lambda: RaftActor(RaftDeviceConfig(n=3, n_proposals=2,
                                                buggy_double_vote=True)),
             EngineConfig(n_nodes=3, outbox_cap=4, queue_cap=64,
                          t_limit_us=1_500_000),
             RAFT_FAULTS),
    "pb": (lambda: PBActor(PBDeviceConfig(n=3, n_writes=4)),
           EngineConfig(n_nodes=3, outbox_cap=4, queue_cap=64,
                        t_limit_us=1_200_000, loss_rate=0.05),
           None),
    "tpc": (lambda: TPCActor(TPCDeviceConfig(n=4, n_txns=4,
                                             buggy_presumed_commit=True)),
            EngineConfig(n_nodes=4, outbox_cap=5, queue_cap=64,
                         t_limit_us=1_200_000, loss_rate=0.1),
            None),
}

_MODES = {
    "plain": dict(pipeline=False),
    "recycled": dict(recycle=True, batch_worlds=16, pipeline=True),
    "pipelined": dict(pipeline=True),
}


@pytest.fixture(scope="module")
def engines():
    """One metrics-off + one metrics-on engine per family, shared across
    the mode matrix (engine builds dominate this module's runtime)."""
    out = {}
    for name, (make_actor, cfg, faults) in _FAMILIES.items():
        out[name] = (
            DeviceEngine(make_actor(), cfg),
            DeviceEngine(make_actor(),
                         dataclasses.replace(cfg, metrics=True)),
            faults,
        )
    return out


@pytest.fixture(scope="module")
def off_sweeps(engines):
    """Memoized recorder-off reference sweeps, shared by the metrics and
    blackbox bitwise matrices (both compare against the IDENTICAL
    off-engine run: same engine instance, seeds 0..39, chunk_steps=64,
    max_steps=3000, family fault template, same orchestration kwargs)."""
    cache = {}

    def get(family, mode):
        if (family, mode) not in cache:
            eng_off, _on, faults = engines[family]
            kw = dict(chunk_steps=64, max_steps=3_000, faults=faults,
                      **_BB_MODES[mode])
            cache[(family, mode)] = sweep(None, eng_off.cfg, np.arange(40),
                                          engine=eng_off, **kw)
        return cache[(family, mode)]

    return get


def test_fault_hist_width_matches_engine_op_range():
    # obs/metrics.py must not import the engine (the engine imports it),
    # so the histogram width is pinned by this assertion instead.
    assert NUM_FAULT_KINDS == FAULT_RESUME + 1


# ---------------------------------------------------------------------------
# Tier-1: bitwise invisibility across families x orchestration modes
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("family", sorted(_FAMILIES))
@pytest.mark.parametrize("mode", sorted(_MODES))
def test_metrics_on_sweep_bitwise_identical(engines, off_sweeps, family,
                                            mode):
    eng_off, eng_on, faults = engines[family]
    seeds = np.arange(40)
    kw = dict(chunk_steps=64, max_steps=3_000, faults=faults,
              **_MODES[mode])
    res_off = off_sweeps(family, mode)
    res_on = sweep(None, eng_on.cfg, seeds, engine=eng_on, **kw)
    # Every non-metrics observation bitwise equal, same occupancy story.
    assert not any(k.startswith("m_") for k in res_off.observations)
    for k, v in res_off.observations.items():
        np.testing.assert_array_equal(v, res_on.observations[k], err_msg=k)
    np.testing.assert_array_equal(res_off.n_active_history,
                                  res_on.n_active_history)
    assert res_off.failing_seeds == res_on.failing_seeds
    assert res_off.steps_run == res_on.steps_run
    # The metrics frames exist, attribute per seed, and are consistent
    # with the engine's own counters.
    assert res_off.metrics is None
    m = res_on.metrics
    assert set(m["per_seed"]) == set(MetricsBlock._fields)
    obs = res_on.observations
    ps = m["per_seed"]
    np.testing.assert_array_equal(
        ps["msgs_delivered"] + ps["timer_fires"], obs["delivered"])
    np.testing.assert_array_equal(
        ps["drop_stale"] + ps["drop_dead"], obs["dropped"])
    np.testing.assert_array_equal(ps["vtime_us"], obs["now_us"])
    np.testing.assert_array_equal(ps["kind_hist"].sum(axis=1),
                                  obs["delivered"])
    if faults is not None:
        # Any world whose clock passed a fault row's time popped that
        # row first (earliest-first pop order): its histogram bin is 1.
        # Worlds frozen earlier (stop_on_bug) legitimately show 0.
        past_kill = obs["now_us"] > 300_000
        past_restart = obs["now_us"] > 700_000
        assert (ps["fault_hist"][past_kill, FAULT_KILL] == 1).all()
        assert (ps["fault_hist"][past_restart, FAULT_RESTART] == 1).all()
        assert (ps["fault_hist"] <= 1).all()
    # The aggregate frame is plain JSON (the bench sim_metrics contract).
    # (No msgs_sent >= msgs_delivered identity: init-scheduled events —
    # proposals, writes — deliver as messages without a send.)
    json.dumps(m["aggregate"])
    agg = m["aggregate"]
    assert agg["msgs_sent"] > 0 and agg["timer_fires"] > 0
    assert agg["drop_loss"] <= agg["msgs_sent"]


@pytest.fixture(scope="module")
def wide_engines():
    """The int32 reference profile (EngineConfig(packed=False)) per
    family — the crosscheck twin of the packed-by-default ``engines``
    fixture (PR "Roofline round 2"; the sequential_insert pattern
    applied to lane dtypes)."""
    out = {}
    for name, (make_actor, cfg, faults) in _FAMILIES.items():
        out[name] = (DeviceEngine(make_actor(),
                                  dataclasses.replace(cfg, packed=False)),
                     faults)
    return out


@pytest.mark.parametrize("family", sorted(_FAMILIES))
@pytest.mark.parametrize("mode", sorted(_MODES))
def test_packed_sweep_bitwise_identical_to_wide(engines, wide_engines,
                                                family, mode):
    """Packed lane dtypes are trajectory-invisible: a packed sweep (the
    default profile — i8/i16 node/code/slot/payload lanes) walks
    bit-identical trajectories to the int32 reference profile, for
    every actor family across the plain/recycled/pipelined orchestration
    modes. Only the at-rest dtypes differ; every observed value, the
    occupancy story, and the failing-seed set must match exactly."""
    eng_packed, _on, faults = engines[family]
    eng_wide, _ = wide_engines[family]
    assert eng_packed.cfg.packed and not eng_wide.cfg.packed
    seeds = np.arange(40)
    kw = dict(chunk_steps=64, max_steps=3_000, faults=faults,
              **_MODES[mode])
    res_p = sweep(None, eng_packed.cfg, seeds, engine=eng_packed, **kw)
    res_w = sweep(None, eng_wide.cfg, seeds, engine=eng_wide, **kw)
    assert set(res_p.observations) == set(res_w.observations)
    for k, v in res_w.observations.items():
        np.testing.assert_array_equal(np.asarray(res_p.observations[k]),
                                      np.asarray(v), err_msg=k)
    np.testing.assert_array_equal(res_p.n_active_history,
                                  res_w.n_active_history)
    assert res_p.failing_seeds == res_w.failing_seeds
    assert res_p.steps_run == res_w.steps_run


def test_metrics_survive_checkpoint_resume(engines, tmp_path):
    """The extra leaf rides the checkpoint format unchanged: a resumed
    metrics-on sweep equals the unbroken run — every MetricsBlock
    counter bit-identical per seed, and the coverage ledger's
    fold-order-invariant halves (hits, first_seen) too. The interrupted
    run retires worlds BEFORE the checkpoint; the resumed call folds
    them through its resume pre-pass (parallel/sweep.py), so ledger
    identity is the property actually under test."""
    _off, eng_on, faults = engines["raft"]
    seeds = np.arange(24)
    full = sweep(None, eng_on.cfg, seeds, engine=eng_on, chunk_steps=128,
                 max_steps=3_000, faults=faults)
    path = str(tmp_path / "m.npz")
    interrupted = sweep(None, eng_on.cfg, seeds, engine=eng_on,
                        chunk_steps=128, max_steps=256, faults=faults,
                        checkpoint_path=path, checkpoint_every_chunks=1)
    resumed = sweep(None, eng_on.cfg, seeds, engine=eng_on, chunk_steps=128,
                    max_steps=3_000, faults=faults, checkpoint_path=path,
                    resume=True)
    for k, v in full.observations.items():
        np.testing.assert_array_equal(v, resumed.observations[k], err_msg=k)
    # Explicitly: the per-seed MetricsBlock frames, counter for counter.
    mf, mr = full.metrics["per_seed"], resumed.metrics["per_seed"]
    assert set(mf) == set(MetricsBlock._fields)
    for k in mf:
        np.testing.assert_array_equal(mf[k], mr[k], err_msg=f"m_{k}")
    # Coverage ledger: hits and first_seen are counts/minima over the
    # folded set, so the resumed run's ledger equals the unbroken one's
    # bit for bit (novelty_curve is per-call history by design).
    cf, cr = full.coverage, resumed.coverage
    assert cf is not None and cr is not None
    np.testing.assert_array_equal(cf.hits, cr.hits)
    np.testing.assert_array_equal(cf.first_seen_seed, cr.first_seen_seed)
    assert cf.distinct_behaviors == cr.distinct_behaviors
    # Sanity that the scenario is non-trivial: some worlds really did
    # retire before the checkpoint cut.
    assert interrupted.n_active_history.size >= 1


# ---------------------------------------------------------------------------
# Tier-1: the behavior-coverage ledger (obs/coverage.py)
# ---------------------------------------------------------------------------

def test_coverage_novelty_curve_contract(engines):
    """SweepResult.coverage acceptance axes: the novelty curve is
    monotone non-decreasing, rides the n_active_history cadence, and is
    bit-deterministic across pipeline on/off; every real seed folds into
    the ledger exactly once (hits sum == n), with first-seen-seed
    attribution consistent with occupancy."""
    _off, eng_on, faults = engines["raft"]
    seeds = np.arange(40)
    kw = dict(chunk_steps=64, max_steps=3_000, faults=faults)
    pip = sweep(None, eng_on.cfg, seeds, engine=eng_on, pipeline=True, **kw)
    ser = sweep(None, eng_on.cfg, seeds, engine=eng_on, pipeline=False, **kw)
    rec = sweep(None, eng_on.cfg, seeds, engine=eng_on, pipeline=True,
                recycle=True, batch_worlds=16, **kw)
    for res in (pip, ser, rec):
        cov = res.coverage
        assert cov is not None
        curve = cov.novelty_curve
        assert curve.shape == res.n_active_history.shape
        assert (np.diff(curve) >= 0).all()
        assert cov.distinct_behaviors >= int(curve[-1])
        assert int(cov.hits.sum()) == len(seeds)  # each seed folded once
        # Bucket attribution: empty buckets carry -1, hit buckets a real
        # seed id (the LOWEST folded in — fold-order invariant).
        fs = cov.first_seen_seed
        assert ((fs == -1) == (cov.hits == 0)).all()
        assert fs[fs >= 0].max(initial=0) < len(seeds)
        assert (np.asarray(cov.new_behaviors_per_chunk).sum()
                == int(curve[-1]) if curve.size else True)
    # Deterministic across orchestration modes: same folded set, same
    # ledger — pipelined == serial == recycled, curve included for the
    # two same-cadence loops.
    np.testing.assert_array_equal(pip.coverage.novelty_curve,
                                  ser.coverage.novelty_curve)
    for a, b in ((pip, ser), (pip, rec)):
        np.testing.assert_array_equal(a.coverage.hits, b.coverage.hits)
        np.testing.assert_array_equal(a.coverage.first_seen_seed,
                                      b.coverage.first_seen_seed)
    # And under an early stop, where the pipelined loop's in-flight
    # superstep must be a ledger pass-through (zero chunks → zero folds)
    # and truncated still-live worlds fold at exit in BOTH loops.
    stop_kw = dict(chunk_steps=64, max_steps=3_000, faults=faults,
                   stop_on_first_bug=True)
    sp = sweep(None, eng_on.cfg, seeds, engine=eng_on, pipeline=True,
               **stop_kw)
    ss = sweep(None, eng_on.cfg, seeds, engine=eng_on, pipeline=False,
               **stop_kw)
    np.testing.assert_array_equal(sp.coverage.hits, ss.coverage.hits)
    np.testing.assert_array_equal(sp.coverage.first_seen_seed,
                                  ss.coverage.first_seen_seed)
    np.testing.assert_array_equal(sp.coverage.novelty_curve,
                                  ss.coverage.novelty_curve)
    assert int(sp.coverage.hits.sum()) == len(seeds)


def test_coverage_ledger_matches_on_multihost_mesh(engines):
    """The ledger's mesh reductions (psum for hits, pmin for first-seen)
    span ALL axes of a 2-D DCN×ICI mesh, so the fleet-scale topology
    (ROADMAP item 1) reports the identical ledger."""
    from madsim_tpu.parallel import multihost_mesh

    _off, eng_on, faults = engines["raft"]
    seeds = np.arange(32)
    kw = dict(chunk_steps=64, max_steps=2_048, faults=faults)
    flat = sweep(None, eng_on.cfg, seeds, engine=eng_on, **kw)
    grid = sweep(None, eng_on.cfg, seeds, engine=eng_on,
                 mesh=multihost_mesh(n_hosts=2), **kw)
    np.testing.assert_array_equal(flat.coverage.hits, grid.coverage.hits)
    np.testing.assert_array_equal(flat.coverage.first_seen_seed,
                                  grid.coverage.first_seen_seed)
    np.testing.assert_array_equal(flat.coverage.novelty_curve,
                                  grid.coverage.novelty_curve)


def test_coverage_distinguishes_faulted_sweep(engines):
    """The novelty signal means something: the same seed set under a
    kill/restart schedule exhibits STRICTLY more distinct behaviors than
    the fault-free run (fault histogram + drop causes hash to fresh
    buckets)."""
    _off, eng_on, faults = engines["raft"]
    seeds = np.arange(40)
    faulted = sweep(None, eng_on.cfg, seeds, engine=eng_on, chunk_steps=64,
                    max_steps=3_000, faults=faults)
    clean = sweep(None, eng_on.cfg, seeds, engine=eng_on, chunk_steps=64,
                  max_steps=3_000)
    assert clean.coverage.distinct_behaviors >= 1
    assert (faulted.coverage.distinct_behaviors
            > clean.coverage.distinct_behaviors)


def test_coverage_requires_metrics(engines):
    eng_off, _on, _f = engines["raft"]
    with pytest.raises(ValueError, match="metrics=True"):
        sweep(None, eng_off.cfg, np.arange(8), engine=eng_off,
              chunk_steps=64, max_steps=256, coverage_buckets=64)
    # Metrics-off sweeps simply report no coverage (and compile the
    # unchanged pre-coverage programs — the op-budget gate's other half).
    res = sweep(None, eng_off.cfg, np.arange(8), engine=eng_off,
                chunk_steps=64, max_steps=256)
    assert res.coverage is None


# ---------------------------------------------------------------------------
# Satellite: trace truncation marker
# ---------------------------------------------------------------------------

def test_trace_truncation_marker_and_warning(engines):
    # The clean PB world runs far past 20 steps: the cut must be marked.
    pb_off, _on, _f = engines["pb"]
    with pytest.warns(RuntimeWarning, match="truncated at max_steps"):
        tr = pb_off.trace(0, max_steps=20)
    assert tr[-1]["kind"] == "truncated"
    assert tr[-1]["step"] == 20 and tr[-1]["bug_seen"] is False
    # A completed world gets NO marker: the buggy raft config freezes on
    # the invariant raise well inside the window.
    eng_off, _on, _f = engines["raft"]
    failing = _first_failing_seed(eng_off)
    full = eng_off.trace(failing, max_steps=4_000)
    assert full[-1]["kind"] != "truncated"
    assert any(e.get("bug_raised") for e in full)


def _first_failing_seed(eng) -> int:
    res = sweep(None, eng.cfg, np.arange(128), engine=eng, chunk_steps=64,
                max_steps=4_000)
    assert res.failing_seeds, "buggy config found no failing seed"
    return res.failing_seeds[0]


# ---------------------------------------------------------------------------
# Timeline export
# ---------------------------------------------------------------------------

def test_chrome_trace_ends_at_invariant_raise(engines):
    eng_off, _on, _f = engines["raft"]
    seed = _first_failing_seed(eng_off)
    tr = eng_off.trace(seed, max_steps=4_000)
    doc = trace_to_chrome(tr, seed=seed)
    blob = json.dumps(doc)  # must be valid JSON end to end
    doc2 = json.loads(blob)
    events = doc2["traceEvents"]
    assert events[0]["ph"] == "M"
    body = [e for e in events if e["ph"] == "i"]
    assert len(body) >= len([e for e in tr if e["kind"] != "truncated"])
    assert events[-1]["name"] == "invariant:raise"
    # Timestamps are the virtual-time microseconds, monotone nondecreasing.
    ts = [e["ts"] for e in body]
    assert ts == sorted(ts)
    assert doc2["otherData"]["clock"] == "virtual_us"
    text = render_text(tr)
    assert "INVARIANT VIOLATION" in text
    assert "truncated" not in text


def test_text_renderer_marks_truncation(engines):
    pb_off, _on, _f = engines["pb"]
    with pytest.warns(RuntimeWarning):
        tr = pb_off.trace(1, max_steps=15)
    text = render_text(tr)
    assert "trace truncated" in text and "bug never seen" in text
    doc = trace_to_chrome(tr, seed=1)
    assert doc["traceEvents"][-1]["name"] == "truncated"


def test_polls_to_chrome_host_trace():
    import madsim_tpu as ms
    from madsim_tpu.obs import polls_to_chrome

    rt = ms.Runtime(seed=3)
    rt.task.trace = polls = []

    async def body():
        from madsim_tpu import time as simtime

        await simtime.sleep(0.05)
        return 7

    assert rt.block_on(body()) == 7
    assert polls, "host runtime recorded no polls"
    doc = polls_to_chrome(polls, seed=3)
    body_evs = [e for e in doc["traceEvents"] if e["ph"] == "i"]
    assert len(body_evs) == len(polls)
    assert body_evs[-1]["ts"] == pytest.approx(polls[-1][1] / 1_000.0)


# ---------------------------------------------------------------------------
# Repro bundles + CLI round trips
# ---------------------------------------------------------------------------

def test_device_bundle_round_trips_through_cli(engines, tmp_path, capsys):
    eng_off, _on, _f = engines["raft"]
    seed = _first_failing_seed(eng_off)
    path = write_sweep_bundle(
        str(tmp_path), seed=seed, actor="raft",
        actor_config=eng_off.actor.rcfg, engine_config=eng_off.cfg,
        max_steps=4_000, error="RaftInvariantViolation: double vote")
    bundle = load_bundle(path)
    assert bundle["kind"] == "device_sweep" and bundle["seed"] == seed
    assert bundle["config_hash"]
    out = str(tmp_path / "trace.json")
    rc = obs_main(["replay", "--bundle", path, "--out", out])
    assert rc == 0, capsys.readouterr().err
    with open(out) as f:
        doc = json.load(f)
    assert doc["traceEvents"][-1]["name"] == "invariant:raise"


def test_device_bundle_unreproduced_failure_exits_nonzero(tmp_path):
    # A bundle claiming a failure on a CLEAN config must not silently
    # "reproduce": the CLI exits 1 when the invariant holds.
    path = write_sweep_bundle(
        str(tmp_path), seed=0, actor="raft",
        actor_config=RaftDeviceConfig(n=3),
        engine_config=EngineConfig(n_nodes=3, outbox_cap=4, queue_cap=64,
                                   t_limit_us=200_000),
        max_steps=2_000, error="RaftInvariantViolation: double vote")
    rc = obs_main(["replay", "--bundle", path,
                   "--out", str(tmp_path / "t.json")])
    assert rc == 1


def test_failing_test_writes_bundle_and_cli_reproduces(tmp_path,
                                                       monkeypatch):
    """The acceptance round trip: a failing @test writes a repro bundle
    (MADSIM_REPRO_DIR), and the CLI replays it to the same bug."""
    monkeypatch.syspath_prepend(FIXTURES)
    monkeypatch.setenv("MADSIM_TEST_SEED", "7")
    monkeypatch.setenv("MADSIM_REPRO_DIR", str(tmp_path))
    monkeypatch.delenv("MADSIM_TEST_BACKEND", raising=False)
    import obs_failing_test

    with pytest.raises(RuntimeError, match="obs bundle fixture failure"):
        obs_failing_test.always_fails()
    bundles = [f for f in os.listdir(tmp_path) if f.endswith(".json")]
    assert len(bundles) == 1, bundles
    path = os.path.join(str(tmp_path), bundles[0])
    bundle = load_bundle(path)
    assert bundle["kind"] == "host_test"
    assert bundle["test"] == "obs_failing_test:always_fails"
    assert bundle["env"]["MADSIM_TEST_SEED"] == "7"
    assert bundle["error"].startswith("RuntimeError")
    # Stop the replayed failure from writing bundle-on-bundle into the
    # assertion above's directory.
    monkeypatch.delenv("MADSIM_REPRO_DIR")
    rc = obs_main(["replay", "--bundle", path])
    assert rc == 0


def test_banner_carries_backend_batch_and_fault_digest(capsys,
                                                       monkeypatch):
    import madsim_tpu as ms

    monkeypatch.delenv("MADSIM_REPRO_DIR", raising=False)
    cfg = ms.Config()
    cfg.net.packet_loss_rate = 0.25
    b = ms.Builder(seed=11, backend="bridge", batch=4, config=cfg)
    b._print_banner(11, error=RuntimeError("x"))
    err = capsys.readouterr().err
    assert "MADSIM_TEST_SEED=11" in err
    assert "MADSIM_CONFIG_HASH=" in err
    assert "MADSIM_FAULT_SHA=" in err
    assert "MADSIM_TEST_BACKEND=bridge" in err
    assert "MADSIM_TEST_BATCH=4" in err
    # The fault digest tracks the fault model, not unrelated config.
    import re

    sha = re.search(r"MADSIM_FAULT_SHA=(\w+)", err).group(1)
    b2 = ms.Builder(seed=11)  # default fault model
    b2._print_banner(11)
    sha2 = re.search(r"MADSIM_FAULT_SHA=(\w+)",
                     capsys.readouterr().err).group(1)
    assert sha != sha2


def test_sweep_result_banner_names_fault_schedule(engines):
    eng_off, _on, faults = engines["raft"]
    res = sweep(None, eng_off.cfg, np.arange(64), engine=eng_off,
                chunk_steps=64, max_steps=4_000, faults=faults)
    banner = res.repro_banner()
    assert banner and "fault-schedule sha256:" in banner
    assert res.faults_sha256


# ---------------------------------------------------------------------------
# Bridge: the kernel's metrics block is trajectory-invisible too
# ---------------------------------------------------------------------------

def test_bridge_metrics_block_is_trajectory_invisible():
    from madsim_tpu.bridge.runtime import _sweep_impl

    async def world():
        from madsim_tpu import time as simtime

        for _ in range(4):
            await simtime.sleep(0.01)
        return 99

    seeds = list(range(6))
    plain_outs, plain_traces = _sweep_impl(world, seeds, trace=True)
    profile: dict = {}
    prof_outs, prof_traces = _sweep_impl(world, seeds, trace=True,
                                         profile=profile)
    assert [o.value for o in plain_outs] == [o.value for o in prof_outs]
    assert plain_traces == prof_traces  # bit-identical poll sequences
    sm = profile["sim_metrics"]
    assert sm["timers_set"] >= 4 * len(seeds)
    assert sm["events_fired"] >= 4 * len(seeds)
    assert sm["vtime_ns"] > 0
    assert sm["msgs_sent"] == 0 and sm["msgs_lost"] == 0
    # The per-slot coverage sketch rides the same one-time metrics pull
    # (obs/coverage.py coverage_of_counters over BridgeMetrics).
    cov = profile["coverage"]
    assert cov["worlds_folded"] == len(seeds)
    assert 1 <= cov["distinct_behaviors"] <= len(seeds)
    import json as _json

    _json.dumps(cov)  # plain JSON: the bench sim_metrics sibling record


# ---------------------------------------------------------------------------
# The flight recorder (obs/blackbox.py + EngineConfig(blackbox=K))
# ---------------------------------------------------------------------------

BB_FIELDS = {"bb_pos", "bb_step_lo", "bb_t_lo", "bb_t_hi",
             "bb_kind", "bb_src", "bb_dst", "bb_flags"}

# The blackbox matrix adds the whole-hunt fused mode: the ring must ride
# the fused loop's per-seed retirement buffers and final scatter exactly
# like the host-orchestrated modes (parallel/sweep.py _fused_hunt).
_BB_MODES = {**_MODES,
             "fused": dict(recycle=True, batch_worlds=16, fused=True)}


@pytest.fixture(scope="module")
def bb_engines():
    """One blackbox-on engine per family (K=8 — small enough that every
    surviving world wraps the ring inside the 3k-step budget)."""
    out = {}
    for name, (make_actor, cfg, faults) in _FAMILIES.items():
        out[name] = (DeviceEngine(make_actor(),
                                  dataclasses.replace(cfg, blackbox=8)),
                     faults)
    return out


@pytest.mark.parametrize("family", sorted(_FAMILIES))
@pytest.mark.parametrize("mode", sorted(_BB_MODES))
def test_blackbox_on_sweep_bitwise_identical(engines, bb_engines,
                                             off_sweeps, family, mode):
    """Tier-1, the metrics contract replayed for the flight recorder: a
    blackbox-on sweep walks bit-identical trajectories to blackbox-off
    on every result surface, for every family across plain / recycled /
    pipelined / fused orchestration, and the ONLY additional observation
    keys are the eight ``bb_*`` ring lanes."""
    eng_off, _on, faults = engines[family]
    eng_bb, _ = bb_engines[family]
    seeds = np.arange(40)
    kw = dict(chunk_steps=64, max_steps=3_000, faults=faults,
              **_BB_MODES[mode])
    res_off = off_sweeps(family, mode)
    res_bb = sweep(None, eng_bb.cfg, seeds, engine=eng_bb, **kw)
    assert set(res_bb.observations) - set(res_off.observations) == BB_FIELDS
    for k, v in res_off.observations.items():
        np.testing.assert_array_equal(np.asarray(v),
                                      np.asarray(res_bb.observations[k]),
                                      err_msg=k)
    np.testing.assert_array_equal(res_off.n_active_history,
                                  res_bb.n_active_history)
    assert res_off.failing_seeds == res_bb.failing_seeds
    assert res_off.steps_run == res_bb.steps_run
    # Off surfaces refuse politely; on surfaces decode. A failing
    # world's ring ends at the invariant raise (stop_on_bug default).
    with pytest.raises(ValueError, match="blackbox-off"):
        res_off.blackbox()
    if res_bb.failing_seeds:
        ring = res_bb.blackbox()
        assert ring and ring[-1]["kind"] != "truncated"
        assert ring[-1].get("bug_raised")


def test_blackbox_ring_wraps_and_matches_trace_suffix(engines, bb_engines):
    """The bitwise ring == trace-suffix contract, past the wrap point:
    with K=8 every surviving world records more than K events, so the
    decoded ring must equal exactly the LAST K entries of the replayed
    ``trace()`` — same dicts, with ``total`` pinning the event count the
    world really processed (a dropped or phantom event cannot hide)."""
    from madsim_tpu.obs import ring_matches_trace
    from madsim_tpu.obs.blackbox import rings_from_observations

    eng_off, _on, faults = engines["raft"]
    eng_bb, _ = bb_engines["raft"]
    seeds = np.arange(12)
    res = sweep(None, eng_bb.cfg, seeds, engine=eng_bb, chunk_steps=64,
                max_steps=3_000, faults=faults)
    pos = np.asarray(res.observations["bb_pos"])
    assert (pos > 8).any(), "no world wrapped the K=8 ring"
    rows = [int(np.argmax(pos > 8))]
    if res.failing_seeds:
        rows.append(int(np.argmax(np.asarray(res.seeds)
                                  == np.uint64(res.failing_seeds[0]))))
    for row in rows:
        seed = int(np.asarray(res.seeds)[row])
        ring = res.blackbox(seed)
        assert len(ring) == min(int(pos[row]), 8)
        trace = eng_off.trace(seed, max_steps=3_000, faults=faults)
        err = ring_matches_trace(ring, trace, total=int(pos[row]))
        assert err is None, err
    # decode_ring validates the step lane against the reconstructed
    # indices: a torn ring raises instead of rendering a wrong timeline.
    from madsim_tpu.obs import decode_ring

    rings = rings_from_observations(res.observations)
    one = {k: np.array(v[rows[0]]) for k, v in rings.items()}
    one["step_lo"] = np.array(one["step_lo"])
    one["step_lo"][0] += 1
    with pytest.raises(ValueError, match="torn"):
        decode_ring(one)


def test_blackbox_survives_checkpoint_resume_and_refuses_mixup(
        engines, bb_engines, tmp_path):
    """Rings ride the checkpoint as WorldState leaves: a resumed
    blackbox-on sweep reproduces the unbroken run's ring lanes bit for
    bit; resuming a blackbox-on checkpoint with a blackbox-off engine
    (or vice versa) is a CheckpointError, not a silent shape surprise."""
    from madsim_tpu.engine.checkpoint import CheckpointError

    eng_bb, faults = bb_engines["raft"]
    _off, eng_on, _ = engines["raft"]
    seeds = np.arange(24)
    full = sweep(None, eng_bb.cfg, seeds, engine=eng_bb, chunk_steps=128,
                 max_steps=3_000, faults=faults)
    path = str(tmp_path / "bb.npz")
    sweep(None, eng_bb.cfg, seeds, engine=eng_bb, chunk_steps=128,
          max_steps=256, faults=faults, checkpoint_path=path,
          checkpoint_every_chunks=1)
    with pytest.raises(CheckpointError, match="different engine config"):
        sweep(None, eng_on.cfg, seeds, engine=eng_on, chunk_steps=128,
              max_steps=3_000, faults=faults, checkpoint_path=path,
              resume=True)
    resumed = sweep(None, eng_bb.cfg, seeds, engine=eng_bb,
                    chunk_steps=128, max_steps=3_000, faults=faults,
                    checkpoint_path=path, resume=True)
    for k in sorted(BB_FIELDS | set(full.observations)):
        np.testing.assert_array_equal(full.observations[k],
                                      resumed.observations[k], err_msg=k)


def test_blackbox_adds_zero_fetches(engines, bb_engines, monkeypatch):
    """Sync discipline: the ring reaches the host entirely through the
    retirement pull and the final merge — a blackbox-on sweep performs
    exactly as many ``_fetch`` calls as the blackbox-off twin."""
    import importlib

    sweep_mod = importlib.import_module("madsim_tpu.parallel.sweep")
    eng_off, _on, faults = engines["raft"]
    eng_bb, _ = bb_engines["raft"]
    counts = []
    real_fetch = sweep_mod._fetch

    def counting_fetch(tree):
        counts.append(1)
        return real_fetch(tree)

    monkeypatch.setattr(sweep_mod, "_fetch", counting_fetch)
    kw = dict(chunk_steps=64, max_steps=3_000, faults=faults,
              pipeline=True)
    res_off = sweep(None, eng_off.cfg, np.arange(40), engine=eng_off, **kw)
    n_off = len(counts)
    counts.clear()
    res_bb = sweep(None, eng_bb.cfg, np.arange(40), engine=eng_bb, **kw)
    assert len(counts) == n_off
    assert res_bb.loop_stats["scalar_fetches"] == \
        res_off.loop_stats["scalar_fetches"]
    assert res_bb.loop_stats["retire_fetches"] == \
        res_off.loop_stats["retire_fetches"]


def test_blackbox_off_compiles_pre_blackbox_program():
    """blackbox-off is not merely cheap — it is the SAME program: the
    off engine's state carries no ring residue (the ``blackbox`` leaf is
    an empty pytree subtree) and its compiled run reproduces the budget
    ledger's ``engine.run`` measurement exactly (flops and argument
    bytes), while the K=64 twin reproduces ``engine.run_blackbox`` —
    both regenerated by tools/update_budgets.py in the blackbox PR."""
    from madsim_tpu.analysis import budgets as _budgets

    ledger = _budgets.load_ledger()
    rcfg = RaftDeviceConfig(n=3, buggy_double_vote=True)
    cfg = EngineConfig(n_nodes=3, outbox_cap=4, queue_cap=64,
                       t_limit_us=2_000_000, stop_on_bug=False)
    measured = {}
    for name, blackbox in (("engine.run", 0), ("engine.run_blackbox", 64)):
        eng = DeviceEngine(RaftActor(rcfg),
                           dataclasses.replace(cfg, blackbox=blackbox))
        state = eng.init(np.arange(256))
        if not blackbox:
            assert state.blackbox is None
        comp = _budgets.compile_fresh(eng._run.lower(state, 4_000))
        ca = comp.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0]
        entry = ledger["programs"][name]
        assert float(ca["flops"]) == entry["flops"]["measured"], name
        ma = comp.memory_analysis()
        assert int(ma.argument_size_in_bytes) == entry["arg_bytes"], name
        measured[name] = float(ca["flops"])
    assert measured["engine.run_blackbox"] > measured["engine.run"]


@pytest.mark.slow
def test_triage_bundle_carries_ring_and_cli_crosschecks(engines, bb_engines,
                                                        tmp_path, capsys):
    """Triage round trip: a blackbox-on sweep's class bundle carries the
    ``madsim.blackbox/1`` block whose decoded ring ends at the invariant
    raise, and ``obs replay --bundle --crosscheck`` verifies ring ==
    replayed-trace suffix bitwise (exit 0; exit 1 once tampered).

    Marked slow (the CLI replay legs recompile the replay engine): the
    fresh-process CLI contract runs in CI via ``make replay-demo``, and
    the tier-1 guided-hunt test keeps the bundle-block + ring-tail +
    crosscheck coverage."""
    from madsim_tpu.triage import triage

    make_actor, cfg, faults = _FAMILIES["raft"]
    # Triage buckets by the MetricsBlock behavior signature, so this
    # engine runs both recorders: metrics AND the ring.
    eng_bb = DeviceEngine(make_actor(),
                          dataclasses.replace(cfg, metrics=True,
                                              blackbox=8))
    res = sweep(None, eng_bb.cfg, np.arange(64), engine=eng_bb,
                chunk_steps=64, max_steps=3_000, faults=faults)
    assert res.failing_seeds
    rep = triage(res, out_dir=str(tmp_path), minimize=False,
                 max_steps=3_000)
    path = next(iter(rep.bundles.values()))
    bundle = load_bundle(path)
    block = bundle["extra"]["blackbox"]
    assert block["schema"] == "madsim.blackbox/1"
    assert block["k"] == 8 and block["n_records"] == len(block["events"])
    assert block["events"][-1].get("bug_raised")
    # The block replays against the ORIGINAL rows it recorded under,
    # carried inside the block (the bundle's top-level rows may be
    # minimized) — here the shared template.
    np.testing.assert_array_equal(np.asarray(block["faults"], np.int32),
                                  faults)
    out = str(tmp_path / "t.json")
    assert obs_main(["replay", "--bundle", path, "--crosscheck",
                     "--out", out]) == 0
    capsys.readouterr()
    bundle["extra"]["blackbox"]["events"][-1]["t_us"] += 1
    with open(path, "w") as f:
        json.dump(bundle, f)
    assert obs_main(["replay", "--bundle", path, "--crosscheck",
                     "--out", out]) == 1
    assert "DIVERGENCE" in capsys.readouterr().err


def test_guided_hunt_blackbox_invisible_and_bundle_ring_ends_at_raise(
        tmp_path):
    """The acceptance pair: (1) the pinned guided pair hunt is bitwise
    unchanged by the flight recorder — same finds, same corpus, same
    schedules; (2) its triage bundle carries a decoded ring whose final
    event is the invariant raise, replaying against the find's
    MATERIALIZED schedule (the block's own recipe)."""
    from madsim_tpu.obs import ring_matches_trace
    from madsim_tpu.search import (
        GuidedPairActor, GuidedPairConfig, engine_config, family_schedule,
    )
    from madsim_tpu.search.family import (
        HUNT_NODES, HUNT_ROWS, hunt_search_config,
    )
    from madsim_tpu.triage import triage

    acfg = GuidedPairConfig(n=HUNT_NODES)
    cfg = engine_config(acfg)
    tmpl = family_schedule(HUNT_ROWS, acfg)
    kw = dict(faults=tmpl, max_steps=10_000_000, recycle=True,
              batch_worlds=32, chunk_steps=32, stop_on_first_bug=True,
              search=hunt_search_config())
    eng_off = DeviceEngine(GuidedPairActor(acfg), cfg)
    eng_bb = DeviceEngine(GuidedPairActor(acfg),
                          dataclasses.replace(cfg, blackbox=8))
    res_off = sweep(None, eng_off.cfg, np.arange(128), engine=eng_off, **kw)
    res_bb = sweep(None, eng_bb.cfg, np.arange(128), engine=eng_bb, **kw)
    assert res_bb.failing_seeds == res_off.failing_seeds
    assert res_bb.failing_seeds, "guided hunt missed the bug in budget"
    np.testing.assert_array_equal(res_bb.search.schedules,
                                  res_off.search.schedules)
    assert set(res_bb.observations) - set(res_off.observations) == BB_FIELDS
    for k, v in res_off.observations.items():
        np.testing.assert_array_equal(np.asarray(v),
                                      np.asarray(res_bb.observations[k]),
                                      err_msg=k)
    rep = triage(res_bb, out_dir=str(tmp_path), minimize=False,
                 max_steps=20_000)
    bundle = load_bundle(next(iter(rep.bundles.values())))
    block = bundle["extra"]["blackbox"]
    assert block["events"][-1].get("bug_raised")
    # In-process crosscheck on the block's own recipe: the recorded
    # ring is bitwise the suffix of the re-traced materialized schedule.
    trace = eng_off.trace(block["seed"], max_steps=block["steps"],
                          faults=np.asarray(block["faults"], np.int32))
    err = ring_matches_trace(block["events"], trace, total=block["n_total"])
    assert err is None, err
