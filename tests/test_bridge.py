"""Host↔device bridge: device decision kernel + lockstep sweep.

The contract under test (VERDICT r3 item 1 / SURVEY §7 stage 4): an
UNMODIFIED host-engine workload swept with the device kernel walks, per
seed, the bit-identical trajectory (poll-by-poll task ids and virtual
timestamps) of a plain ``Runtime.block_on`` run — while timers,
next-event selection, clocks, and loss/latency sampling execute batched
on the device.
"""
import pytest

import madsim_tpu as ms
from madsim_tpu import time as vtime
from madsim_tpu.bridge import sweep, sweep_traced
from madsim_tpu.core.task import Deadlock, TimeLimitExceeded
from madsim_tpu.net import Endpoint, NetSim, rpc

SEEDS = list(range(6))


def host_run(world_fn, seed, config=None, time_limit=None):
    rt = ms.Runtime(seed=seed, config=config)
    if time_limit is not None:
        rt.set_time_limit(time_limit)
    tr = []
    rt.task.trace = tr
    val = rt.block_on(world_fn())
    return val, tr


def assert_identical(world_fn, seeds, *, config_fn=None, configs_fn=None,
                     **kw):
    cfgs = [configs_fn() for _ in seeds] if configs_fn else None
    outs, trs = sweep_traced(
        world_fn, seeds,
        config=config_fn() if config_fn else None,
        configs=cfgs, **kw)
    for i, s in enumerate(seeds):
        hv, htr = host_run(world_fn, s,
                           config=(cfgs[i] if cfgs else
                                   config_fn() if config_fn else None))
        assert outs[i].error is None, (s, outs[i].error)
        assert outs[i].value == hv, (s, outs[i].value, hv)
        assert trs[i] == htr, (
            f"seed {s}: trajectory diverged at poll "
            f"{next(j for j, (a, b) in enumerate(zip(trs[i], htr)) if a != b)}"
        )
    return outs


class Ping:
    __slots__ = ("n",)

    def __init__(self, n):
        self.n = n


async def _await(f):
    return await f


# ---------------------------------------------------------------------------


def test_bridge_sleep_world_bit_identical():
    async def world():
        t0 = vtime.monotonic()
        await vtime.sleep(0.5)
        await vtime.sleep(0.25)
        return round(vtime.monotonic() - t0, 9)

    assert_identical(world, SEEDS)


def _pingpong_world(rounds=8, timeout=0.3, payload=b"x" * 32):
    async def world():
        h = ms.Handle.current()

        async def server_init():
            ep = await Endpoint.bind("10.0.0.1:9000")

            async def handle(req, data):
                return Ping(req.n + 1), data

            rpc.add_rpc_handler_with_data(ep, Ping, handle)
            await vtime.sleep(1e6)

        h.create_node(name="server", ip="10.0.0.1", init=server_init)
        client = h.create_node(name="client", ip="10.0.0.2")
        done = ms.sync.SimFuture()

        async def client_body():
            ep = await Endpoint.bind("10.0.0.2:0")
            got = 0
            for i in range(rounds):
                while True:
                    try:
                        r, _ = await rpc.call_with_data(
                            ep, "10.0.0.1:9000", Ping(i), payload,
                            timeout=timeout)
                        got += r.n
                        break
                    except TimeoutError:
                        pass
            done.set_result(got)

        client.spawn(client_body())
        return await vtime.timeout(600, _await(done))

    return world


def test_bridge_rpc_pingpong_bit_identical():
    # The VERDICT "done" workload: bench config 1's 2-node RPC ping-pong,
    # swept with the device kernel, bit-identical to pure-host runs.
    assert_identical(_pingpong_world(), SEEDS)


def test_bridge_chaos_bit_identical():
    # Loss + partitions + node restart: the device samples every loss /
    # latency decision, the host injects faults — trajectories still match.
    def world_fn():
        async def world():
            h = ms.Handle.current()

            async def server_init():
                ep = await Endpoint.bind("10.0.0.1:9000")

                async def handle(req):
                    return req.n * 2

                rpc.add_rpc_handler(ep, Ping, handle)
                await vtime.sleep(1e6)

            server = h.create_node(name="server", ip="10.0.0.1",
                                   init=server_init)
            client = h.create_node(name="client", ip="10.0.0.2")
            done = ms.sync.SimFuture()

            async def client_body():
                ep = await Endpoint.bind("10.0.0.2:0")
                got = 0
                for i in range(10):
                    while True:
                        try:
                            got += await rpc.call(ep, "10.0.0.1:9000",
                                                  Ping(i), timeout=0.3)
                            break
                        except TimeoutError:
                            pass
                done.set_result(got)

            client.spawn(client_body())

            async def chaos():
                sim = ms.simulator(NetSim)
                for k in range(3):
                    await vtime.sleep(0.5)
                    if k % 2 == 0:
                        sim.disconnect2(server.id, client.id)
                        await vtime.sleep(0.2)
                        sim.connect2(server.id, client.id)
                    else:
                        h.restart(server.id)

            ms.task.spawn(chaos())
            return await vtime.timeout(600, _await(done))

        return world

    def cfg():
        c = ms.Config()
        c.net.packet_loss_rate = 0.08
        return c

    assert_identical(world_fn(), SEEDS[:4], config_fn=cfg)


def test_bridge_config_grid_axis():
    # The (seeds x configs) axis: one sweep, each world its own loss rate,
    # each bit-identical to a host run under that config. The reference
    # can only hold one network config per run (network.rs:74-94).
    world = _pingpong_world(rounds=5)
    losses = (0.0, 0.15)
    seeds, cfgs = [], []
    for s in range(3):
        for p in losses:
            c = ms.Config()
            c.net.packet_loss_rate = p
            seeds.append(s)
            cfgs.append(c)
    outs, trs = sweep_traced(world, seeds, configs=cfgs)
    i = 0
    for s in range(3):
        for p in losses:
            c = ms.Config()
            c.net.packet_loss_rate = p
            hv, htr = host_run(world, s, config=c)
            assert outs[i].error is None
            assert outs[i].value == hv
            assert trs[i] == htr, (s, p)
            i += 1
    # Different loss rates must actually change trajectories (the axis is
    # real, not a broadcast of one config). Any seed may get lucky with no
    # losses in a short run; across three seeds at 15% loss at least one
    # pair must diverge.
    assert any(trs[2 * i] != trs[2 * i + 1] for i in range(3))


def test_bridge_batched_sweep_bit_identical():
    """World recycling on the bridge (sweep(batch=...)): seeds stream
    through a bounded set of kernel slots, each retired slot re-keyed for
    the next seed (BridgeKernel.reset_slot). Trajectories must stay
    bit-identical to pure-host runs — the slot a world lands in, and
    whoever occupied it before, must be invisible to the world."""
    assert_identical(_pingpong_world(rounds=4), SEEDS, batch=2)
    # And a batch that doesn't divide the seed count.
    assert_identical(_pingpong_world(rounds=4), SEEDS[:5], batch=3)


def test_bridge_batched_sweep_mixed_outcomes():
    # Recycling must keep error attribution straight: odd seeds raise,
    # even seeds return their value, across several slot generations.
    async def world(seed):
        await vtime.sleep(0.1)
        if seed % 2:
            raise ValueError(f"boom {seed}")
        return seed * 10

    outs = sweep(world, list(range(9)), batch=2)
    for seed, o in enumerate(outs):
        assert o.seed == seed
        if seed % 2:
            assert isinstance(o.error, ValueError) and str(seed) in str(o.error)
        else:
            assert o.error is None and o.value == seed * 10


def test_bridge_deadlock_and_time_limit():
    async def deadlocked():
        await _await(ms.sync.SimFuture())  # never resolved, no timers

    outs = sweep(deadlocked, [1, 2])
    assert all(isinstance(o.error, Deadlock) for o in outs)
    # Pure host agrees.
    with pytest.raises(Deadlock):
        ms.Runtime(seed=1).block_on(deadlocked())

    async def forever():
        while True:
            await vtime.sleep(1.0)

    outs = sweep(forever, [1, 2], time_limit=5.0)
    assert all(isinstance(o.error, TimeLimitExceeded) for o in outs)


def test_bridge_drain_rounds_bit_identical():
    """A due cluster wider than k_events forces drain rounds. The drain
    chain is pop-only and dispatch-ahead since round 8
    (``BridgeKernel.drain``: round r+1 is dispatched before round r's
    events are unpacked/fired, and the speculative tail round pops
    nothing) — the cluster must still fire in exact host-heap
    (deadline, seq) order, checked poll-for-poll against the pure host
    Runtime."""
    N = 11

    async def world():
        order = []

        async def sleeper(i):
            # One shared deadline plus a few staggered ones: the cluster
            # at t=0.5 drains k_events=2 per round over several rounds.
            await vtime.sleep(0.5 if i % 3 else 0.5 + 0.001 * i)
            order.append(i)

        for i in range(N):
            ms.task.spawn(sleeper(i))
        await vtime.sleep(2.0)
        return tuple(order)

    assert_identical(world, SEEDS[:3], k_events=2)


def test_bridge_task_error_propagates():
    async def boom():
        await vtime.sleep(0.1)
        raise ValueError("kaboom")

    outs = sweep(boom, [3])
    assert isinstance(outs[0].error, ValueError)


def test_bridge_timer_capacity_error_is_actionable():
    async def many_sleepers():
        async def sleeper():
            await vtime.sleep(1.0)

        for _ in range(40):
            ms.task.spawn(sleeper())
        await vtime.sleep(2.0)

    outs = sweep(many_sleepers, [1], cap=8)
    assert isinstance(outs[0].error, RuntimeError)
    assert "cap" in str(outs[0].error)


def test_bridge_jobs_sharding():
    # jobs=2 runs task bodies across forked pool workers behind one
    # shared kernel (bridge/pool.py, MADSIM_TEST_JOBS analog); same
    # outcomes, by seed order. The fresh-interpreter leg exercises the
    # cold path (no warm jit caches, no prior fork); the in-process leg
    # pools from a jax-live parent — the pool's own determinism matrix
    # lives in tests/test_bridge_pool.py.
    import subprocess
    import sys
    import textwrap

    src = textwrap.dedent("""
        import sys
        sys.path.insert(0, %r)
        import madsim_tpu as ms
        from madsim_tpu import time as vtime
        from madsim_tpu.bridge import sweep

        async def world():
            s = ms.Handle.current().seed
            await vtime.sleep(0.05)
            return s + 100

        outs = sweep(world, [4, 7, 1, 9], jobs=2)
        assert [(o.seed, o.value, o.error) for o in outs] == [
            (4, 104, None), (7, 107, None), (1, 101, None), (9, 109, None)], outs
        print("JOBS_OK")
    """) % str(__import__("pathlib").Path(__file__).resolve().parent.parent)
    proc = subprocess.run([sys.executable, "-c", src], capture_output=True,
                          text=True, timeout=300)
    assert "JOBS_OK" in proc.stdout, (proc.stdout, proc.stderr)

    # In-process fallback path (jax already live in this test session).
    async def world():
        s = ms.Handle.current().seed
        await vtime.sleep(0.05)
        return s + 100

    outs = sweep(world, [4, 7], jobs=2)
    assert [(o.seed, o.value) for o in outs] == [(4, 104), (7, 107)]


def test_bridge_mixed_completion_and_results():
    # Worlds finishing at very different virtual times don't disturb each
    # other's lanes; results land by seed order.
    async def world():
        s = ms.Handle.current().seed
        await vtime.sleep(0.01 * (s + 1))
        return s * 10

    outs = sweep(world, [5, 0, 2])
    assert [(o.seed, o.value) for o in outs] == [(5, 50), (0, 0), (2, 20)]
