"""Cross-range corpus exchange (madsim_tpu/fleet/exchange.py,
docs/fleet.md "Corpus exchange").

The PR 12 contract:

- the HOST merge fold is bit-identical to the DEVICE corpus insertion
  fold (the PR 9 twin-parity pattern);
- exchange epochs are structural (range-id partition) and the barrier
  is keyed to completed lease quanta, so a chaotic exchanged fleet —
  kills mid-epoch (kill→re-lease re-seeds from the last merged epoch),
  torn publishes, duplicated completions, dropped RPCs — equals a
  crash-free exchanged fleet BITWISE, including the materialized
  per-seed schedules and the merged corpus;
- epoch-0 ranges are bitwise identical to a non-exchanged fleet's, and
  a single-epoch exchange (cadence >= range count) is bitwise identical
  to ``exchange=None`` end to end — the machinery is invisible when
  there is nothing to exchange;
- duplicate publishes dedupe by range id with bitwise crosscheck
  (tampered duplicates raise FleetIntegrityError); torn publishes are
  discarded and re-sent;
- the coordinator's exchange state persists (fsync+rename) and a
  resumed coordinator re-derives every merged epoch bit-exactly;
- ``sweep(search_corpus=)`` seeding with the template-initialized
  corpus is bitwise invisible and adds ZERO host syncs (counted
  through the ``_fetch`` hook).

Compile budget: one module-scoped family engine at the same
(batch_worlds=32, chunk_steps=32) shapes as tests/test_search.py, so
the jit + persistent caches amortize.
"""
import importlib
import json

import numpy as np
import pytest

from madsim_tpu.engine import DeviceEngine
from madsim_tpu.fleet import (
    ChaosConfig,
    CorpusExchange,
    ExchangeConfig,
    FleetIntegrityError,
    FleetStalledError,
    TornPayloadError,
    fleet_sweep,
    split_ranges,
)
from madsim_tpu.fleet.exchange import (
    GEN_STRIDE,
    corpus_payload,
    payload_corpus,
)
from madsim_tpu.search import (
    GuidedPairActor,
    GuidedPairConfig,
    engine_config,
    family_schedule,
)
from madsim_tpu.search.corpus import (
    EMPTY_NOVELTY,
    HostCorpus,
    corpus_init,
    harvest_fold,
    host_corpus_init,
    host_harvest_fold,
    merge_corpus,
)
from madsim_tpu.search.family import HUNT_NODES, HUNT_ROWS, hunt_search_config

sweep_mod = importlib.import_module("madsim_tpu.parallel.sweep")
sweep = sweep_mod.sweep

BATCH = dict(recycle=True, batch_worlds=32, chunk_steps=32)
N_SEEDS = 96
RANGE = 48  # > batch_worlds, so refills (and harvests) actually run


@pytest.fixture(scope="module")
def hunt():
    acfg = GuidedPairConfig(n=HUNT_NODES)
    cfg = engine_config(acfg)
    eng = DeviceEngine(GuidedPairActor(acfg), cfg)
    return eng, cfg, family_schedule(HUNT_ROWS, acfg)


def _fleet(eng, cfg, tmpl, exchange=None, chaos=None, n_workers=2,
           n_seeds=N_SEEDS, range_size=RANGE, **kw):
    return fleet_sweep(None, cfg, np.arange(n_seeds), engine=eng,
                       faults=tmpl, n_workers=n_workers,
                       range_size=range_size, max_steps=10_000_000,
                       search=hunt_search_config(True), exchange=exchange,
                       chaos=chaos, **BATCH, **kw)


def assert_bitwise(a, b, search=True):
    np.testing.assert_array_equal(a.seeds, b.seeds)
    np.testing.assert_array_equal(a.bug, b.bug)
    assert set(a.observations) == set(b.observations)
    for k in a.observations:
        np.testing.assert_array_equal(np.asarray(a.observations[k]),
                                      np.asarray(b.observations[k]),
                                      err_msg=k)
    if search:
        assert (a.search is None) == (b.search is None)
        if a.search is not None:
            np.testing.assert_array_equal(a.search.schedules,
                                          b.search.schedules)
            for f in ("corpus_sched", "corpus_sig", "corpus_score",
                      "corpus_filled", "corpus_entry", "corpus_depth"):
                np.testing.assert_array_equal(
                    getattr(a.search, f), getattr(b.search, f), err_msg=f)
            # The lineage surface (obs/lineage.py) is chaos-invariant
            # too: ancestry attribution and operator accounting must
            # not depend on kills, duplicates, or torn publishes.
            la, lb = a.search.lineage, b.search.lineage
            assert (la is None) == (lb is None)
            if la is not None:
                for f in ("parent1", "parent2", "ops", "depth"):
                    np.testing.assert_array_equal(
                        getattr(la, f), getattr(lb, f),
                        err_msg=f"lineage.{f}")
                assert a.search.operator_stats == b.search.operator_stats


# ---------------------------------------------------------------------------
# The twin: host merge fold == device insertion fold, bit for bit
# ---------------------------------------------------------------------------

def test_host_fold_parity_with_device(hunt):
    """The exchange merge rides host_harvest_fold, which must reproduce
    the device harvest_fold exactly — ties, novelty gating, worst-first
    replacement, empty-corpus scoring — else a seeded range would
    derive different children than the chaos contract demands."""
    import jax.numpy as jnp

    _eng, _cfg, tmpl = hunt
    rng = np.random.RandomState(7)
    for trial in range(12):
        k = int(rng.randint(1, 7))
        w = int(rng.randint(1, 9))
        mn = int(rng.randint(1, 5))
        sched = rng.randint(-1, 60, size=(w, tmpl.shape[0], 4)) \
            .astype(np.int32)
        sigs = rng.randint(0, 2**32, size=(w,),
                           dtype=np.uint64).astype(np.uint32)
        mask = rng.rand(w) < 0.7
        entries = rng.randint(1, 500, size=(w,)).astype(np.int32)
        depths = rng.randint(0, 9, size=(w,)).astype(np.int32)
        dev = corpus_init(k, tmpl)
        host = host_corpus_init(k, tmpl)
        for _round in range(2):  # fold twice: non-fresh corpus state too
            dev, nd, dnov, dins = harvest_fold(
                dev, jnp.asarray(sched), jnp.asarray(sigs),
                jnp.asarray(mask), mn, entries=jnp.asarray(entries),
                depths=jnp.asarray(depths), with_masks=True)
            host, nh, hnov, hins = host_harvest_fold(
                host, sched, sigs, mask, mn, entries=entries,
                depths=depths, with_masks=True)
            assert int(nd) == nh
            # The outcome-fold masks the operator table credits from
            # (obs/lineage.py) must agree too — the host/device
            # outcome-fold parity half of the PR 13 contract.
            np.testing.assert_array_equal(np.asarray(dnov), hnov)
            np.testing.assert_array_equal(np.asarray(dins), hins)
            for name in ("sched", "sig", "score", "filled", "entry",
                         "depth"):
                np.testing.assert_array_equal(
                    np.asarray(getattr(dev, name)),
                    np.asarray(getattr(host, name)),
                    err_msg=f"trial {trial} field {name}")
            sigs = rng.randint(0, 2**32, size=(w,),
                               dtype=np.uint64).astype(np.uint32)
    # Host init matches the device init arrays (the epoch-0 seed).
    d0, h0 = corpus_init(4, tmpl), host_corpus_init(4, tmpl)
    for name in ("sched", "sig", "score", "filled", "entry", "depth"):
        np.testing.assert_array_equal(np.asarray(getattr(d0, name)),
                                      np.asarray(getattr(h0, name)))


# ---------------------------------------------------------------------------
# Epoch partition, barrier, merge chain (pure host units)
# ---------------------------------------------------------------------------

def _mk_exchange(n_ranges=4, every=2, k=4, tmpl=None, **kw):
    tmpl = tmpl if tmpl is not None else family_schedule(HUNT_ROWS)
    return CorpusExchange(ranges=split_ranges(n_ranges * 8, 8),
                          every=every, template=tmpl, corpus_k=k,
                          min_novelty=1, **kw)


def _snap(tmpl, k=4, sigs=(9,)):
    c = host_corpus_init(k, tmpl)
    sched = np.broadcast_to(tmpl, (len(sigs),) + tmpl.shape)
    c, _ = host_harvest_fold(c, sched, np.asarray(sigs, np.uint32),
                             np.ones(len(sigs), bool), 1)
    return c


def test_epoch_barrier_and_merge_chain():
    tmpl = family_schedule(HUNT_ROWS)
    ex = _mk_exchange(n_ranges=4, every=2, tmpl=tmpl)
    assert [ex.epoch_of(r) for r in range(4)] == [0, 0, 1, 1]
    assert ex.gen0_of(0) == 0 and ex.gen0_of(2) == GEN_STRIDE
    # Epoch-0 ranges are eligible from the start; epoch-1 blocked.
    assert ex.eligible(0) and ex.eligible(1)
    assert not ex.eligible(2)
    assert "exchange barrier" in ex.blocked_reason(2)
    assert ex.seed_corpus(0) is None  # epoch 0 = template (no payload)
    s0, s1 = _snap(tmpl, sigs=(9,)), _snap(tmpl, sigs=(12,))
    assert ex.publish(0, corpus_payload(s0), worker="w0")["accepted"]
    assert not ex.eligible(2)  # half-published epoch: still blocked
    assert ex.publish(1, corpus_payload(s1), worker="w1")["accepted"]
    # Barrier lifted; the merged corpus is the manual range-id fold.
    assert ex.eligible(2) and ex.merged_through() == 1
    want, _ = merge_corpus(ex.base, s0, 1)
    want, _ = merge_corpus(want, s1, 1)
    got = ex.seed_corpus(2)
    for name in ("sched", "sig", "score", "filled"):
        np.testing.assert_array_equal(np.asarray(getattr(got, name)),
                                      np.asarray(getattr(want, name)))
    assert ex.stats["epochs_merged"] == 1


def test_duplicate_publish_dedupe_tamper_and_torn():
    tmpl = family_schedule(HUNT_ROWS)
    ex = _mk_exchange(n_ranges=2, every=1, tmpl=tmpl)
    snap = _snap(tmpl, sigs=(9,))
    assert ex.publish(0, corpus_payload(snap))["accepted"]
    # Bitwise-identical duplicate (restarted worker): absorbed.
    out = ex.publish(0, corpus_payload(snap))
    assert out["accepted"] and out["duplicate"]
    assert ex.stats["publishes_duplicate"] == 1
    # Tampered duplicate: the determinism contract is broken — loud.
    bad = HostCorpus(sched=snap.sched.copy(), sig=snap.sig.copy(),
                     score=snap.score.copy(), filled=snap.filled.copy(),
                     entry=snap.entry.copy(), depth=snap.depth.copy())
    bad.sig[0] ^= np.uint32(1)
    with pytest.raises(FleetIntegrityError, match="bitwise"):
        ex.publish(0, corpus_payload(bad))
    # Torn publish: checksum mismatch → discarded, resend requested.
    torn = corpus_payload(_snap(tmpl, sigs=(5,)))
    torn["sched"] = torn["sched"].copy()
    torn["sched"].flat[0] ^= 1
    out = ex.publish(1, torn)
    assert not out["accepted"] and out["torn"]
    assert ex.stats["publishes_torn"] == 1
    assert not ex.has(1)
    # The clean re-send goes through.
    assert ex.publish(1, corpus_payload(_snap(tmpl, sigs=(5,))))["accepted"]
    # Shape tears and checksum validation at the payload layer.
    with pytest.raises(TornPayloadError, match="checksum"):
        payload_corpus(torn)
    with pytest.raises(TornPayloadError, match="missing"):
        payload_corpus({"sched": torn["sched"]})
    with pytest.raises(TornPayloadError, match="entries"):
        payload_corpus(corpus_payload(snap), corpus_k=9)


def test_coordinator_crash_resume_is_bit_exact(tmp_path):
    """Coordinator killed between merge and broadcast: a fresh exchange
    reloading the persisted snapshots re-derives the identical merged
    corpus (the merge is a deterministic fold of the stored inputs),
    and continuing publishes into the resumed exchange ends at the same
    final chain as the uninterrupted one."""
    tmpl = family_schedule(HUNT_ROWS)
    path = str(tmp_path / "exchange_state.npz")
    a = _mk_exchange(n_ranges=4, every=2, tmpl=tmpl, state_path=path)
    snaps = [_snap(tmpl, sigs=(int(s),)) for s in (9, 12, 33, 70)]
    a.publish(0, corpus_payload(snaps[0]))
    a.publish(1, corpus_payload(snaps[1]))  # epoch 0 merged + persisted
    assert a.merged_through() == 1
    # "Crash": build a brand-new exchange from the same fleet shape and
    # resume from disk. The merged chain must match bit for bit.
    b = _mk_exchange(n_ranges=4, every=2, tmpl=tmpl, state_path=path)
    assert b.resume(path) == 2
    assert b.merged_through() == 1
    for name in ("sched", "sig", "score", "filled"):
        np.testing.assert_array_equal(
            np.asarray(getattr(a.merged_epoch(0), name)),
            np.asarray(getattr(b.merged_epoch(0), name)), err_msg=name)
    # Continue both to the end: identical final chains.
    for ex in (a, b):
        ex.publish(2, corpus_payload(snaps[2]))
        ex.publish(3, corpus_payload(snaps[3]))
        assert ex.merged_through() == 2
    for name in ("sched", "sig", "score", "filled"):
        np.testing.assert_array_equal(
            np.asarray(getattr(a.merged_epoch(1), name)),
            np.asarray(getattr(b.merged_epoch(1), name)), err_msg=name)
    # A mismatched fleet shape is refused loudly.
    with pytest.raises(FleetIntegrityError, match="different fleet"):
        _mk_exchange(n_ranges=4, every=1, tmpl=tmpl).resume(path)


# ---------------------------------------------------------------------------
# The fleet legs (device sweeps; shapes shared with test_search)
# ---------------------------------------------------------------------------

def test_exchanged_fleet_chaotic_equals_clean_and_workers_invariant(hunt):
    """The acceptance matrix in one pass: a clean exchanged fleet ==
    a chaotic one (kill mid-epoch → re-lease re-seeded from the last
    merged epoch, duplicated completions, torn publish, dropped RPCs)
    == a single-worker fleet over the same partition+cadence — bitwise
    on ids/observations/bug/schedules/merged corpus."""
    eng, cfg, tmpl = hunt
    clean = _fleet(eng, cfg, tmpl, exchange=ExchangeConfig(every=1))
    chaotic = _fleet(
        eng, cfg, tmpl, exchange=ExchangeConfig(every=1),
        chaos=ChaosConfig(seed=7, kill_at=(("w1", 2),),
                          duplicate_all_completions=True,
                          tear_publish_at=(("w0", 1),),
                          drop_rpc_rate=0.2, restart_after=2))
    solo = _fleet(eng, cfg, tmpl, exchange=ExchangeConfig(every=1),
                  n_workers=1)
    assert_bitwise(clean, chaotic)
    assert_bitwise(clean, solo)
    st = chaotic.loop_stats["fleet"]
    assert st["kills"] >= 1, "the kill→re-lease leg must have fired"
    assert st["leases_reissued"] >= 1
    assert st["publishes_torn"] >= 1
    assert st["duplicate_completions"] >= 1
    assert st["epochs_merged"] == 2
    # The exchange visibly did something: a later epoch was seeded and
    # the merged corpus grew past the template.
    workers = st["workers"]
    assert sum(w["corpus_seeded"] for w in workers.values()) >= 1
    assert clean.search is not None
    assert clean.search.corpus_size >= 2


def test_epoch0_matches_plain_fleet_and_seeding_changes_later_epochs(hunt):
    """Epoch-0 ranges run at generation offset 0 from the template
    corpus — bitwise identical to a non-exchanged fleet's — while
    seeded epochs run different children (the exchange actually bites).
    And with a cadence spanning every range (single epoch), the whole
    exchanged fleet is bitwise == exchange=None: the machinery is
    invisible when there is nothing to exchange."""
    eng, cfg, tmpl = hunt
    plain = _fleet(eng, cfg, tmpl, exchange=None)
    exchanged = _fleet(eng, cfg, tmpl, exchange=ExchangeConfig(every=1))
    # Epoch 0 = seeds [0, RANGE): bitwise equal to the plain fleet.
    for k in plain.observations:
        np.testing.assert_array_equal(
            np.asarray(plain.observations[k])[:RANGE],
            np.asarray(exchanged.observations[k])[:RANGE], err_msg=k)
    # Epoch 1 = seeds [RANGE, N): the merged-corpus seeding + stream
    # offset changed the children somewhere.
    assert any(
        not np.array_equal(np.asarray(plain.observations[k])[RANGE:],
                           np.asarray(exchanged.observations[k])[RANGE:])
        for k in plain.observations), \
        "exchange seeding left epoch-1 ranges bitwise unchanged — the " \
        "merged corpus is not reaching the sweeps"
    # Single epoch (cadence >= range count): end-to-end bitwise == None.
    single = _fleet(eng, cfg, tmpl, exchange=ExchangeConfig(every=2),
                    n_workers=1)
    assert_bitwise(plain, single, search=False)
    assert plain.search is None and single.search is not None
    st = single.loop_stats["fleet"]
    assert st["publishes"] == 2 and st["epochs_merged"] == 1


def test_exchanged_fleet_resumes_coordinator_state_end_to_end(hunt,
                                                             tmp_path):
    """A second fleet run over a pre-populated exchange state (the
    coordinator crash→restart shape): every range's snapshot is already
    published, so publishes dedupe as bitwise-checked duplicates and
    the final result equals the fresh run exactly."""
    eng, cfg, tmpl = hunt
    path = str(tmp_path / "exchange_state.npz")
    fresh = _fleet(eng, cfg, tmpl,
                   exchange=ExchangeConfig(every=1, state_path=path))
    resumed = _fleet(eng, cfg, tmpl,
                     exchange=ExchangeConfig(every=1, state_path=path))
    assert_bitwise(fresh, resumed)
    st = resumed.loop_stats["fleet"]
    # All snapshots were already on disk: the re-publishes are
    # crosschecked duplicates, and the barrier never blocked.
    assert st["publishes"] == 0
    assert st["publishes_duplicate"] == 2


# ---------------------------------------------------------------------------
# sweep(search_corpus=): bitwise-invisible seeding, zero extra syncs
# ---------------------------------------------------------------------------

def test_search_corpus_template_seed_bitwise_invisible_and_no_new_syncs(
        hunt, monkeypatch):
    eng, cfg, tmpl = hunt
    scfg = hunt_search_config(True)

    def run(**kw):
        calls = []
        real = sweep_mod._fetch

        def counting(tree):
            calls.append(1)
            return real(tree)

        monkeypatch.setattr(sweep_mod, "_fetch", counting)
        try:
            res = sweep(None, cfg, np.arange(64), engine=eng, faults=tmpl,
                        max_steps=10_000_000, search=scfg, **BATCH, **kw)
        finally:
            monkeypatch.setattr(sweep_mod, "_fetch", real)
        return res, len(calls)

    base, n_base = run()
    seeded, n_seeded = run(
        search_corpus=host_corpus_init(scfg.corpus, tmpl))
    # The template-initialized host corpus IS corpus_init: bitwise
    # invisible, and the host→device seeding adds zero _fetch calls.
    assert n_seeded == n_base
    assert (base.bug == seeded.bug).all()
    for k in base.observations:
        np.testing.assert_array_equal(np.asarray(base.observations[k]),
                                      np.asarray(seeded.observations[k]),
                                      err_msg=k)
    np.testing.assert_array_equal(base.search.schedules,
                                  seeded.search.schedules)
    np.testing.assert_array_equal(base.search.corpus_sched,
                                  seeded.search.corpus_sched)
    assert base.search.generations == seeded.search.generations


def test_search_corpus_and_gen0_validation(hunt):
    eng, cfg, tmpl = hunt
    scfg = hunt_search_config(True)
    with pytest.raises(ValueError, match="search=SearchConfig"):
        sweep(None, cfg, np.arange(8), engine=eng, faults=tmpl,
              max_steps=256,
              search_corpus=host_corpus_init(scfg.corpus, tmpl), **BATCH)
    with pytest.raises(ValueError, match="search=SearchConfig"):
        sweep(None, cfg, np.arange(8), engine=eng, faults=tmpl,
              max_steps=256, search_gen0=GEN_STRIDE, **BATCH)
    # Wrong K: the error names both dims (corpus entries vs config).
    with pytest.raises(ValueError, match=r"\(K, F, 4\).*corpus=32"):
        sweep(None, cfg, np.arange(8), engine=eng, faults=tmpl,
              max_steps=256, search=scfg,
              search_corpus=host_corpus_init(scfg.corpus // 2, tmpl),
              **BATCH)
    # Exchange-side validation at the fleet entry.
    with pytest.raises(ValueError, match="search=SearchConfig"):
        fleet_sweep(None, cfg, np.arange(16), engine=eng, faults=tmpl,
                    exchange=ExchangeConfig(), max_steps=256, **BATCH)
    with pytest.raises(ValueError, match="inline"):
        fleet_sweep(None, cfg, np.arange(16), engine=eng, faults=tmpl,
                    exchange=ExchangeConfig(), search=hunt_search_config(
                        True), spawn="process", max_steps=256, **BATCH)
    with pytest.raises(ValueError, match="every"):
        ExchangeConfig(every=0)


# ---------------------------------------------------------------------------
# FleetStalledError detail (satellite): names ranges, holders, beats
# ---------------------------------------------------------------------------

def test_stalled_error_names_ranges_holders_and_heartbeats(hunt):
    eng, cfg, tmpl = hunt
    with pytest.raises(FleetStalledError) as exc:
        fleet_sweep(None, cfg, np.arange(64), engine=eng, faults=tmpl,
                    n_workers=1, range_size=16, max_steps=10_000_000,
                    search=hunt_search_config(True),
                    exchange=ExchangeConfig(every=1),
                    chaos=ChaosConfig(seed=1, kill_at=(("w0", 1),),
                                      restart_after=-1), **BATCH)
    msg = str(exc.value)
    # The stuck range, its holder, and the heartbeat bookkeeping are in
    # the message — plus the exchange-barrier diagnosis for the ranges
    # the merge gate is holding back.
    assert "range 0: held by w0" in msg
    assert "last heartbeat" in msg and "expires t=" in msg
    assert "exchange barrier" in msg
