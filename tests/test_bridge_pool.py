"""Forked worker pool behind the bridge kernel (bridge/pool.py).

The contract under test (ROADMAP item 4): ``sweep(jobs=J)`` runs the W
live worlds' Python task bodies across J forked workers behind ONE
shared device decision kernel, and per-seed traces, send accounting, and
mixed-outcome attribution stay BIT-IDENTICAL to ``jobs=1`` and to the
serial in-process loop — for every J, every batch width, and every
W % J remainder, exactly as ``bridge.sweep(batch=N)`` gates batching.
Worker death mid-round must raise a pointed BridgePoolError (no hangs)
and leave no orphaned shared-memory segments.
"""
import glob
import os
import signal

import pytest

import madsim_tpu as ms
from madsim_tpu import time as vtime
from madsim_tpu.bridge import sweep, sweep_traced
from madsim_tpu.bridge.pool import BridgePoolError, sweep_pooled
from madsim_tpu.bridge.runtime import PackBufferCache
from madsim_tpu.net import Endpoint, rpc

SEEDS = list(range(12))


class Ping:
    __slots__ = ("n",)

    def __init__(self, n):
        self.n = n


async def _await(f):
    return await f


def _pingpong_world(rounds=4, timeout=0.3):
    """Bench-config-1-shaped RPC world; returns (sum, msg_count) so the
    outcome VALUE carries the send accounting the kernel's loss draws
    decide — any accounting divergence fails the value equality."""

    async def world():
        from madsim_tpu.net import NetSim

        h = ms.Handle.current()

        async def server_init():
            ep = await Endpoint.bind("10.0.0.1:9000")

            async def handle(req):
                return req.n + 1

            rpc.add_rpc_handler(ep, Ping, handle)
            await vtime.sleep(1e6)

        h.create_node(name="server", ip="10.0.0.1", init=server_init)
        client = h.create_node(name="client", ip="10.0.0.2")
        done = ms.sync.SimFuture()

        async def client_body():
            ep = await Endpoint.bind("10.0.0.2:0")
            got = 0
            for i in range(rounds):
                while True:
                    try:
                        got += await rpc.call(ep, "10.0.0.1:9000", Ping(i),
                                              timeout=timeout)
                        break
                    except TimeoutError:
                        pass
            done.set_result(got)

        client.spawn(client_body())
        got = await vtime.timeout(600, _await(done))
        stat = ms.simulator(NetSim).network.stat
        return got, stat.msg_count

    return world


def _lossy_cfg(p=0.1):
    c = ms.Config()
    c.net.packet_loss_rate = p
    return c


def _key(outs):
    return [(o.seed, o.value, type(o.error).__name__ if o.error else None,
             str(o.error) if o.error else None) for o in outs]


# ---------------------------------------------------------------------------


def test_pool_bitwise_identical_matrix():
    """jobs=J == jobs=1 (one pooled worker) == serial, bitwise on traces
    + outcomes + send accounting, for J x batch including non-dividing
    W % J remainders (batch=5 over J=4 slices as 2/1/1/1)."""
    world = _pingpong_world()
    serial, tr_serial = sweep_traced(world, SEEDS, config=_lossy_cfg())
    for batch in (None, 5):
        ref, tr_ref = sweep_traced(world, SEEDS, config=_lossy_cfg(),
                                   batch=batch)
        assert tr_ref == tr_serial, f"serial batch={batch} diverged"
        for jobs in (1, 2, 3, 4):
            outs, trs = sweep_pooled(world, SEEDS, jobs=jobs,
                                     config=_lossy_cfg(), trace=True,
                                     batch=batch)
            assert trs == tr_serial, (jobs, batch)
            assert _key(outs) == _key(serial), (jobs, batch)


def test_pool_config_grid_and_remainder_seeds():
    """Per-world configs slice correctly across worker seed shards, and a
    seed count that divides into uneven shards attributes by position."""
    world = _pingpong_world(rounds=3)
    seeds, cfgs = [], []
    for s in range(7):  # 7 seeds over 3 workers: shards of 3/2/2
        seeds.append(s)
        cfgs.append(_lossy_cfg(0.0 if s % 2 else 0.15))
    serial, tr_serial = sweep_traced(world, seeds, configs=cfgs)
    outs, trs = sweep_pooled(world, seeds, jobs=3, configs=cfgs, trace=True)
    assert trs == tr_serial
    assert _key(outs) == _key(serial)


def test_pool_mixed_outcomes_with_recycling():
    """Error attribution across slot generations under jobs x batch: odd
    seeds raise, even seeds return — same contract as the serial
    bridge's batched sweep (test_bridge_batched_sweep_mixed_outcomes)."""

    async def world(seed):
        await vtime.sleep(0.1 * (seed % 3 + 1))
        if seed % 2:
            raise ValueError(f"boom {seed}")
        return seed * 10

    for jobs in (2, 3):
        outs = sweep(world, list(range(9)), jobs=jobs, batch=2)
        for seed, o in enumerate(outs):
            assert o.seed == seed
            if seed % 2:
                assert isinstance(o.error, ValueError)
                assert str(seed) in str(o.error)
            else:
                assert o.error is None and o.value == seed * 10


def test_pool_single_seed_and_tiny_batches():
    """Degenerate widths: jobs clamps to W (batch=1 -> one worker), a
    single seed routes through unchanged."""

    async def world():
        await vtime.sleep(0.05)
        return ms.Handle.current().seed + 100

    serial, tr = sweep_traced(world, [7])
    outs, trs = sweep_pooled(world, [7], jobs=4, trace=True)
    assert trs == tr and _key(outs) == _key(serial)
    serial6, tr6 = sweep_traced(world, list(range(6)))
    outs6, trs6 = sweep_pooled(world, list(range(6)), jobs=4, trace=True,
                               batch=1)
    assert trs6 == tr6 and _key(outs6) == _key(serial6)


def test_pool_drain_rounds_bit_identical():
    """Due clusters wider than k_events force drain rounds; the pool's
    shared-memory drain scatter must fire them in exact host-heap
    (deadline, seq) order per world."""
    N = 11

    async def world():
        order = []

        async def sleeper(i):
            await vtime.sleep(0.5 if i % 3 else 0.5 + 0.001 * i)
            order.append(i)

        for i in range(N):
            ms.task.spawn(sleeper(i))
        await vtime.sleep(2.0)
        return tuple(order)

    serial, tr = sweep_traced(world, SEEDS[:4], k_events=2)
    outs, trs = sweep_pooled(world, SEEDS[:4], jobs=2, trace=True,
                             k_events=2)
    assert trs == tr
    assert _key(outs) == _key(serial)


def test_pool_fetch_seam_counts_only_drains(monkeypatch):
    """Sync discipline: the parent round loop's only blocking drain
    materializations route through the sanctioned pool._fetch seam —
    a drain-free sweep crosses it zero times."""
    from madsim_tpu.bridge import pool as pool_mod

    calls = []
    real = pool_mod._fetch
    monkeypatch.setattr(pool_mod, "_fetch",
                        lambda x: (calls.append(1), real(x))[1])

    async def world():
        await vtime.sleep(0.05)
        return ms.Handle.current().seed

    sweep_pooled(world, SEEDS[:4], jobs=2)
    assert calls == [], "non-drain round crossed the blocking seam"


def test_pool_worker_crash_raises_pointed_error():
    """SIGKILL one worker mid-round: the parent must raise BridgePoolError
    naming worker/slot-range/round (no hang, no partial batch) and unlink
    every shared-memory segment."""
    parent = os.getpid()

    async def world():
        s = ms.Handle.current().seed
        await vtime.sleep(0.1)
        if s == 6 and os.getpid() != parent:
            os.kill(os.getpid(), signal.SIGKILL)  # die mid host burst
        return s

    with pytest.raises(BridgePoolError) as ei:
        sweep_pooled(world, list(range(8)), jobs=2)
    err = ei.value
    assert err.worker == 1 and err.slots == (4, 8)
    assert err.round_no is not None and err.round_no >= 1
    assert "worker 1" in str(err) and "slots 4..7" in str(err)
    assert f"round {err.round_no}" in str(err)
    if os.path.isdir("/dev/shm"):  # the no-orphaned-segments contract
        assert glob.glob("/dev/shm/msbp-*") == []


@pytest.mark.slow
def test_pool_process_leg_fresh_interpreter():
    """PR 7-style process leg: the whole pool pipeline in a fresh
    interpreter (cold jit caches, cold fork server), crash leg included."""
    import subprocess
    import sys
    import textwrap

    src = textwrap.dedent("""
        import glob, os, signal, sys
        sys.path.insert(0, %r)
        import madsim_tpu as ms
        from madsim_tpu import time as vtime
        from madsim_tpu.bridge import sweep_traced
        from madsim_tpu.bridge.pool import BridgePoolError, sweep_pooled

        async def world():
            s = ms.Handle.current().seed
            await vtime.sleep(0.05 * (s %% 3 + 1))
            return s + 100

        serial, tr = sweep_traced(world, list(range(8)))
        outs, trs = sweep_pooled(world, list(range(8)), jobs=2, trace=True)
        assert trs == tr and [o.value for o in outs] == \\
            [o.value for o in serial]

        parent = os.getpid()

        async def crasher():
            s = ms.Handle.current().seed
            await vtime.sleep(0.1)
            if s == 3 and os.getpid() != parent:
                os.kill(os.getpid(), signal.SIGKILL)
            return s

        try:
            sweep_pooled(crasher, list(range(4)), jobs=2)
            raise SystemExit("crash leg did not raise")
        except BridgePoolError as e:
            assert e.worker == 1, e
        if os.path.isdir("/dev/shm"):
            assert glob.glob("/dev/shm/msbp-*") == []
        print("POOL_PROC_OK")
    """) % str(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    proc = subprocess.run([sys.executable, "-c", src], capture_output=True,
                          text=True, timeout=300,
                          env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert "POOL_PROC_OK" in proc.stdout, (proc.stdout, proc.stderr)


def test_pack_buffer_cache_is_bounded():
    """The per-(W, T, C, S) pack-buffer cache must not accumulate without
    limit across sweeps/rounds with varying shapes (LRU bound), while
    still returning the SAME arrays for a repeated shape."""
    cache = PackBufferCache(maxsize=8)
    first = cache.get(4, 4, 4, 4)
    assert cache.get(4, 4, 4, 4)[0] is first[0]  # hit: same storage
    for t in range(30):  # 30 distinct shapes stream through
        cache.get(8, 4 << (t % 5), 4, 4 << (t // 5))
    assert len(cache) <= 8
    # a key kept recent survives further churn (LRU, not FIFO)
    hot = cache.get(4, 4, 4, 4)
    for t in range(6):
        cache.get(16, 4, 4 << t, 4)
        assert cache.get(4, 4, 4, 4)[0] is hot[0]
    assert len(cache) <= 8


def test_module_pack_cache_bounded_across_sweeps():
    """Re-sweeping many widths must not grow the process-global cache
    past its bound (each W is a distinct key)."""
    from madsim_tpu.bridge import runtime as rt_mod

    async def world():
        await vtime.sleep(0.05)
        return ms.Handle.current().seed

    for w in range(1, 12):
        sweep(world, list(range(w)))
    assert len(rt_mod._PACK_BUFFERS) <= rt_mod._PACK_BUFFERS.maxsize


def test_builder_jobs_routes_bridge_backend():
    """MADSIM_TEST_JOBS / Builder(jobs=) reaches the pool on the bridge
    backend: same last-seed result as jobs=1."""
    from madsim_tpu.testing import Builder

    async def body():
        await vtime.sleep(0.05)
        return ms.Handle.current().seed * 3

    r1 = Builder(seed=5, count=6, jobs=1, backend="bridge").run(body)
    r2 = Builder(seed=5, count=6, jobs=2, backend="bridge").run(body)
    assert r1 == r2 == 10 * 3
