"""File-system simulator tests (`fs.rs:259-296` + the power_fail semantics
the reference left as a TODO)."""
import pytest

import madsim_tpu as ms
from madsim_tpu import fs, time


def test_create_write_read():
    rt = ms.Runtime(seed=1)
    node = rt.create_node(name="n1")

    async def work():
        f = await fs.File.create("/data")
        await f.write_all_at(b"hello world", 0)
        assert await f.read_at(0, 5) == b"hello"
        assert await f.read_at(6, 100) == b"world"
        assert (await f.metadata()).len == 11
        await f.set_len(5)
        assert await f.read_all() == b"hello"
        assert await fs.read("/data") == b"hello"

    h = node.spawn(work())

    async def main():
        await h

    rt.block_on(main())


def test_open_missing_file():
    rt = ms.Runtime(seed=1)
    node = rt.create_node(name="n1")

    async def work():
        with pytest.raises(FileNotFoundError):
            await fs.File.open("/missing")

    h = node.spawn(work())

    async def main():
        await h

    rt.block_on(main())


def test_fs_is_per_node():
    rt = ms.Runtime(seed=1)
    n1 = rt.create_node(name="n1")
    n2 = rt.create_node(name="n2")

    async def writer():
        await fs.write("/f", b"n1-data")

    async def reader():
        with pytest.raises(FileNotFoundError):
            await fs.read("/f")

    async def main():
        await n1.spawn(writer())
        await n2.spawn(reader())

    rt.block_on(main())


def test_power_fail_loses_unsynced_data():
    """Kill = power failure: synced data survives, unsynced is lost."""
    rt = ms.Runtime(seed=1)
    results = {}

    async def init():
        f = await fs.File.open_or_create("/wal")
        existing = await f.read_all()
        if existing:
            results["after_crash"] = existing
            return
        await f.write_all_at(b"durable", 0)
        await f.sync_all()
        await f.write_all_at(b"volatile", 7)
        # no sync — crash loses this
        await time.sleep(1000.0)

    node = rt.create_node(name="db", init=init)

    async def main():
        await time.sleep(1.0)
        ms.Handle.current().restart(node)
        await time.sleep(1.0)
        assert results["after_crash"] == b"durable"

    rt.block_on(main())


def test_disk_survives_restart():
    rt = ms.Runtime(seed=1)
    seen = []

    async def init():
        f = await fs.File.open_or_create("/state")
        data = await f.read_all()
        seen.append(bytes(data))
        await f.set_len(0)
        await f.write_all_at(b"gen%d" % len(seen), 0)
        await f.sync_all()
        await time.sleep(1000.0)

    node = rt.create_node(name="db", init=init)

    async def main():
        await time.sleep(1.0)
        ms.Handle.current().restart(node)
        await time.sleep(1.0)
        ms.Handle.current().restart(node)
        await time.sleep(1.0)
        assert seen == [b"", b"gen1", b"gen2"]

    rt.block_on(main())


def test_remove_file():
    rt = ms.Runtime(seed=1)
    node = rt.create_node(name="n1")

    async def work():
        await fs.write("/tmpf", b"x")
        await fs.remove_file("/tmpf")
        with pytest.raises(FileNotFoundError):
            await fs.read("/tmpf")

    h = node.spawn(work())

    async def main():
        await h

    rt.block_on(main())
