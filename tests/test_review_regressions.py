"""Regression tests for defects found in code review (round 1)."""
import pytest

import madsim_tpu as ms
from madsim_tpu import net, task, time
from madsim_tpu.net import Endpoint, NetSim
from madsim_tpu.net import rpc as msrpc


def test_main_node_can_use_network():
    """The block_on root task (main node, id 0) must be known to NetSim."""
    rt = ms.Runtime(seed=1)

    async def main():
        ep = await Endpoint.bind("127.0.0.1:100")
        assert ep.local_addr() == ("127.0.0.1", 100)

    rt.block_on(main())


def test_node_killing_itself():
    """A task calling kill() on its own node must not crash the sim."""
    rt = ms.Runtime(seed=1)
    log = []

    async def suicidal():
        log.append("up")
        await time.sleep(0.1)
        ms.Handle.current().kill(node)
        log.append("after-kill")  # runs until next await, then dropped

    node = rt.create_node(name="kamikaze", init=suicidal)

    async def main():
        await time.sleep(5.0)
        assert "up" in log

    rt.block_on(main())


def test_check_determinism_with_config_mutation():
    """In-sim config mutation must not leak between checker runs."""
    cfg = ms.Config()

    async def main():
        sim = ms.simulator(NetSim)
        sim.update_config(lambda c: setattr(c, "packet_loss_rate", c.packet_loss_rate + 0.4))
        for _ in range(20):
            await time.sleep(ms.rand.random())

    ms.Runtime.check_determinism(0, cfg, main)
    assert cfg.net.packet_loss_rate == 0.0, "caller's config must not be polluted"


def test_recv_cancelled_in_processing_delay_requeues():
    """A message taken from the mailbox but not delivered (receiver cancelled
    during the post-receive delay) must be requeued, not lost."""
    rt = ms.Runtime(seed=1)
    n1 = rt.create_node(name="n1", ip="10.0.0.1")
    n2 = rt.create_node(name="n2", ip="10.0.0.2")

    async def sender():
        ep = await Endpoint.bind(("10.0.0.1", 1))
        await ep.send_to(("10.0.0.2", 1), 7, b"precious")

    async def receiver():
        ep = await Endpoint.bind(("10.0.0.2", 1))
        # Try many tight timeouts: some cancel mid-delay across seeds.
        got = None
        for _ in range(200):
            try:
                got = await time.timeout(1e-6, ep.recv_from(7))
                break
            except TimeoutError:
                continue
        if got is None:
            got = await time.timeout(10.0, ep.recv_from(7))
        assert got[0] == b"precious"

    n1.spawn(sender())
    h = n2.spawn(receiver())

    async def main():
        await h

    rt.block_on(main())


def test_endpoint_close_with_rpc_handler_is_clean():
    """Closing an endpoint with a registered handler must not abort the sim."""
    rt = ms.Runtime(seed=1)
    node = rt.create_node(name="srv", ip="10.0.0.1")

    class Req:
        pass

    async def server():
        ep = await Endpoint.bind(("10.0.0.1", 1))

        async def on_req(_req):
            return "ok"

        msrpc.add_rpc_handler(ep, Req, on_req)
        await time.sleep(1.0)
        ep.close()

    h = node.spawn(server())

    async def main():
        await h
        await time.sleep(5.0)  # dispatcher must have exited cleanly

    rt.block_on(main())


def test_aborted_tasks_do_not_leak():
    """timeout() aborts its runner; NodeInfo.tasks must not grow unboundedly."""
    rt = ms.Runtime(seed=1)

    async def main():
        for _ in range(100):
            with pytest.raises(TimeoutError):
                await time.timeout(0.001, time.sleep(10.0))
        node = ms.Handle.current().task.main_node.info
        assert len(node.tasks) < 10, f"leaked {len(node.tasks)} task entries"

    rt.block_on(main())


def test_node_liveness_api():
    """NodeHandle.is_alive reflects kill/restart (review round 2)."""
    rt = ms.Runtime(seed=1)
    node = rt.create_node(name="n")

    async def main():
        assert node.is_alive()
        ms.Handle.current().kill(node)
        assert not node.is_alive()
        ms.Handle.current().restart(node)
        assert node.is_alive()

    rt.block_on(main())


def test_raft_leader_persists_to_own_disk():
    """Leader-side start() must persist to the leader's node disk, not the
    caller's (review round 2)."""
    from madsim_tpu.models.raft import RaftCluster
    from madsim_tpu import fs as msfs

    rt = ms.Runtime(seed=13)
    rt.set_time_limit(120.0)

    async def main():
        cluster = RaftCluster(3)
        leader = await cluster.wait_for_leader()
        await cluster.propose("precious")
        await time.sleep(0.5)

        # main node disk must NOT have raft state
        async def read_main():
            try:
                return await msfs.read("/raft-state")
            except FileNotFoundError:
                return None

        assert await read_main() is None, "raft state leaked onto main node disk"
        # leader's own disk must have it
        blob = {}

        async def read_leader():
            blob["b"] = await msfs.read("/raft-state")

        await cluster.nodes[leader].spawn(read_leader())
        import pickle
        term, voted, log = pickle.loads(blob["b"])
        assert any(cmd == "precious" for _, cmd in log)

    rt.block_on(main())


def test_wait_for_leader_after_kill_excludes_dead_node():
    from madsim_tpu.models.raft import RaftCluster

    rt = ms.Runtime(seed=21)
    rt.set_time_limit(120.0)

    async def main():
        cluster = RaftCluster(3)
        first = await cluster.wait_for_leader()
        cluster.kill(first)
        assert cluster.leader() != first, "dead node must not be reported leader"
        second = await cluster.wait_for_leader(timeout=30.0)
        assert second != first

    rt.block_on(main())


def test_bindguard_has_no_gc_time_side_effects():
    """Releasing a port from __del__ would mutate sim state at a moment set
    by the process's allocation history (GC cycles), not the seed — the
    order-dependent sweep failure found in round 2. Guard against it
    structurally: no __del__ on BindGuard, and close() is token-checked."""
    from madsim_tpu.net.netsim import BindGuard

    assert not hasattr(BindGuard, "__del__"), \
        "BindGuard.__del__ reintroduces GC-timing nondeterminism"


def test_stale_bindguard_close_cannot_release_successor_binding():
    """After a node reset + rebind of the same address, a leftover guard
    from the previous generation must not close the new socket."""
    from madsim_tpu.net import Endpoint, rpc
    from madsim_tpu import time as simtime

    rt = ms.Runtime(seed=5)
    rt.set_time_limit(60.0)

    async def main():
        h = ms.Handle.current()
        stale = {}

        class Echo:
            def __init__(self, n):
                self.n = n

        async def server_init():
            ep = await Endpoint.bind("10.0.0.1:7000")
            if "guard" not in stale:
                stale["guard"] = ep._guard  # first generation's guard

            async def handle(req):
                return Echo(req.n)

            rpc.add_rpc_handler(ep, Echo, handle)
            await simtime.sleep(1e6)

        server = h.create_node(name="srv", ip="10.0.0.1", init=server_init)
        client = h.create_node(name="cli", ip="10.0.0.2")
        await simtime.sleep(0.5)
        h.restart(server)          # reset clears gen-1 binding; init rebinds
        await simtime.sleep(0.5)
        stale["guard"].close()     # stale close: must be a no-op

        async def probe():
            ep = await Endpoint.bind("10.0.0.2:0")
            rsp = await rpc.call(ep, "10.0.0.1:7000", Echo(42), timeout=5.0)
            assert rsp.n == 42

        await client.spawn(probe())

    rt.block_on(main())


def test_task_set_iteration_is_insertion_ordered():
    """kill() drops a node's tasks by iterating NodeInfo.tasks; the
    container must iterate in insertion order (dict), never address order
    (set), or drop side effects diverge across processes."""
    from madsim_tpu.core.task import NodeInfo

    info = NodeInfo(0, "n", 1)
    assert isinstance(info.tasks, dict)


def test_timer_beyond_2_62_ns_fires_identically_on_bridge():
    """ADVICE r4 (medium): the bridge kernel's empty-lane sentinel used to
    sit at 2^62 while deadlines clamped at 2^63-1, so a timer in
    [2^62, 2^63) was invisible to has_timer and sweep() reported a
    spurious Deadlock where the host engine advanced. Both wheels now
    clamp at TIMER_MAX_NS = 2^62 - 1 (one below the sentinel)."""
    from madsim_tpu.bridge import sweep
    from madsim_tpu.core.timewheel import TIMER_MAX_NS

    async def world():
        await time.sleep(5e9)  # 5e18 ns > 2^62 ns: lands in the clamp zone
        return ms.Handle.current().time.elapsed_ns

    rt = ms.Runtime(seed=7)
    host_ns = rt.block_on(world())
    assert host_ns > TIMER_MAX_NS  # clamped deadline + advance epsilon

    (out,) = sweep(world, [7])
    assert out.error is None, out.error
    assert out.value == host_ns


def test_frame_parser_zero_length_oob_buffer():
    """pickle's buffer_callback collects every out-of-band PickleBuffer,
    including 0-byte ones (empty numpy arrays). The parser must neither
    reject them nor stall on a frame ending in a zero-size section."""
    import numpy as np

    from madsim_tpu.real.net import _FrameProtocol, _encode_frames

    payload = {"big": b"x" * 5000, "empty": np.zeros(0, dtype=np.uint8),
               "tail": np.arange(4, dtype=np.int32)}
    frames = _encode_frames(7, payload)
    wire = b"".join(bytes(f) for f in frames)

    got = []
    proto = _FrameProtocol()  # no handshake: parsing starts at frame head
    proto.sink = lambda tag, data, peer: got.append((tag, data))
    # Feed byte-by-byte: the zero-size sections must finalize eagerly even
    # when they are the last bytes fed.
    for i in range(len(wire)):
        mv = proto.get_buffer(1)
        mv[0] = wire[i]
        proto.buffer_updated(1)
    assert len(got) == 1 and got[0][0] == 7
    data = got[0][1]
    assert data["big"] == payload["big"]
    assert data["empty"].size == 0
    assert list(data["tail"]) == [0, 1, 2, 3]
