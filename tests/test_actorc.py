"""Actor-compiler tests (madsim_tpu/actorc/, docs/actorc.md).

The tier-1 gates of the subsystem:

- host-twin parity: the generated plain-Python interpreter agrees with
  the compiled device actor on per-event state/outbox/bug decisions
  over sampled faulted trajectories, for the migrated families (tpc,
  pb) AND the DSL-only one (paxos) — and the oracle actually CATCHES a
  backend divergence when one is planted;
- spec validation: the packed-width guards and malformed declarations
  surface as pointed SpecErrors naming the offending lane/message/word,
  never as deep trace-time failures;
- lowering contracts: dtype selection from declared ranges, generated
  kind_names rendering in traces, the one-draw discipline, restart
  (disk-vs-memory) annotations;
- the Paxos family itself: clean runs are safe, the forgetful-acceptor
  bug is reachable through well-placed restarts only.
"""
import dataclasses

import jax.numpy as jnp
import numpy as np
import pytest

from madsim_tpu.actorc import (
    ActorSpec, CompiledActor, HostActor, HostTwinMismatch, Lane, Message,
    SpecError, Word, crosscheck,
)
from madsim_tpu.actorc.spec import lane_dtype, validate_spec
from madsim_tpu.engine import DeviceEngine, EngineConfig
from madsim_tpu.engine.core import FAULT_KILL, FAULT_RESTART
from madsim_tpu.engine.lanes import PACKED, WIDE


# ---------------------------------------------------------------------------
# A minimal well-formed spec the validation tests mutate.
# ---------------------------------------------------------------------------
def _ping_spec(n=3, hi=100, word_hi=100, name="ping"):
    def h_ping(c):
        cnt = c.read("count")
        c.write("count", cnt + 1)
        c.send("Ping", dst=(c.me + 1) % n, words=[c.arg("x")],
               when=cnt < 5)

    def init(c):
        c.event("Ping", time=10, dst=0, words=[1])

    return ActorSpec(
        name=name, n_nodes=n,
        lanes=(Lane("count", hi=hi),),
        messages=(Message("Ping", (Word("x", 0, word_hi),)),),
        handlers={"Ping": h_ping},
        init=init,
        invariant=lambda v: v.np.any(v.lane("count") > 1_000_000),
    )


# ---------------------------------------------------------------------------
# Host-twin parity — the conformance oracle (acceptance criterion).
# ---------------------------------------------------------------------------
def test_host_twin_parity_tpc():
    from madsim_tpu.actorc.families.tpc import tpc_spec
    from madsim_tpu.engine import TPCDeviceConfig

    tcfg = TPCDeviceConfig(n=4, n_txns=4, buggy_presumed_commit=True)
    cfg = EngineConfig(n_nodes=4, outbox_cap=5, queue_cap=64,
                       t_limit_us=2_000_000, loss_rate=0.1)
    faults = np.array([[200_000, FAULT_KILL, 0, 0],
                       [500_000, FAULT_RESTART, 0, 0]], np.int32)
    rep = crosscheck(tpc_spec(tcfg), cfg, seeds=[0, 3], faults=faults,
                     max_steps=250)
    assert rep["events_delivered"] > 20
    assert rep["restarts"] >= 1


def test_host_twin_parity_pb():
    from madsim_tpu.actorc.families.pb import pb_spec
    from madsim_tpu.engine import PBDeviceConfig

    pcfg = PBDeviceConfig(n=3, n_writes=3, buggy_commit_early=True)
    cfg = EngineConfig(n_nodes=3, outbox_cap=4, queue_cap=48,
                       t_limit_us=2_000_000, loss_rate=0.2)
    faults = np.array([[130_000, FAULT_KILL, 0, 0],
                       [900_000, FAULT_RESTART, 0, 0]], np.int32)
    # pb's on_restart draws (watchdog re-arm): the restart leg checks
    # the recorded-entropy path of the twin.
    rep = crosscheck(pb_spec(pcfg), cfg, seeds=[0, 9], faults=faults,
                     max_steps=250)
    assert rep["events_delivered"] > 20
    assert rep["restarts"] >= 1


def test_host_twin_parity_paxos():
    from madsim_tpu.actorc.families.paxos import (PaxosConfig,
                                                  engine_config,
                                                  paxos_spec)

    xcfg = PaxosConfig(buggy_forgetful_acceptor=True, contend_all=True)
    faults = np.array([[80_000, FAULT_RESTART, 2, 0]], np.int32)
    rep = crosscheck(paxos_spec(xcfg), engine_config(xcfg),
                     seeds=[0, 1, 5], faults=faults, max_steps=250)
    assert rep["events_delivered"] > 30


def test_host_twin_catches_backend_divergence():
    """The oracle is only worth its compile time if it FAILS when the
    two backends disagree: plant a transition that writes different
    values under jnp and numpy."""
    spec = _ping_spec()

    def evil(c):
        # Branches on the backend — exactly the kind of out-of-surface
        # behavior the crosscheck exists to catch.
        val = 1 if c.np is jnp else 2
        c.write("count", c.read("count") + val)

    spec = dataclasses.replace(spec, handlers={"Ping": evil})
    cfg = EngineConfig(n_nodes=3, outbox_cap=4, queue_cap=16,
                       t_limit_us=1_000_000)
    with pytest.raises(HostTwinMismatch, match="count"):
        crosscheck(spec, cfg, seeds=[0], max_steps=10)


# ---------------------------------------------------------------------------
# Spec validation: pointed errors, not trace-time failures.
# ---------------------------------------------------------------------------
def test_packed_n_nodes_guard_names_the_spec():
    spec = _ping_spec(n=200, name="wide_ping")
    # EngineConfig itself refuses packed 200-node clusters (its own
    # pointed guard), so reaching the SPEC-level guard needs a config
    # stand-in — validate_spec must still name the spec and the escape
    # hatch rather than deferring to a trace-time failure.
    fake = type("Cfg", (), {"n_nodes": 200, "packed": True, "m": 201,
                            "payload_words": 8})()
    with pytest.raises(SpecError, match="wide_ping.*n_nodes=200.*int8"):
        validate_spec(spec, fake)
    # The wide profile accepts the same spec end to end.
    validate_spec(spec, EngineConfig(n_nodes=200, outbox_cap=201,
                                     queue_cap=16, t_limit_us=1_000_000,
                                     packed=False))
    # And a spec/config width mismatch is a SpecError naming both.
    with pytest.raises(SpecError, match="n_nodes=200.*n_nodes=3"):
        validate_spec(spec, EngineConfig(n_nodes=3, outbox_cap=4,
                                         queue_cap=16,
                                         t_limit_us=1_000_000))


def test_payload_word_overflow_names_message_and_word():
    spec = _ping_spec(word_hi=100_000)
    cfg = EngineConfig(n_nodes=3, outbox_cap=4, queue_cap=16,
                       t_limit_us=1_000_000)
    with pytest.raises(SpecError,
                       match="'Ping'.*'x'.*100000.*int16"):
        validate_spec(spec, cfg)
    # ...and the same guard fires through the engine path before any
    # trace-time failure could.
    eng = DeviceEngine(CompiledActor(spec), cfg)
    with pytest.raises(SpecError, match="'Ping'.*'x'"):
        eng.init(np.arange(2))


def test_outbox_capacity_guard():
    spec = _ping_spec()
    cfg = EngineConfig(n_nodes=3, outbox_cap=6, queue_cap=16,
                       t_limit_us=1_000_000)
    with pytest.raises(SpecError, match="n_nodes \\+ 1 = 4, got 6"):
        validate_spec(spec, cfg)


def test_malformed_specs_are_pointed():
    base = _ping_spec()
    with pytest.raises(SpecError, match="unknown message 'Pong'"):
        CompiledActor(dataclasses.replace(
            base, handlers={"Pong": lambda c: None}))
    with pytest.raises(SpecError, match="inverted"):
        CompiledActor(dataclasses.replace(
            base, lanes=(Lane("count", lo=5, hi=2),)))
    with pytest.raises(SpecError, match="duplicate lane"):
        CompiledActor(dataclasses.replace(
            base, lanes=(Lane("count", hi=1), Lane("count", hi=1))))
    with pytest.raises(SpecError, match="on_restart hook"):
        CompiledActor(dataclasses.replace(
            base, lanes=(Lane("g", hi=5, scope="world",
                              durable=False),)))
    with pytest.raises(SpecError, match="counter lanes"):
        spec = dataclasses.replace(
            base, handlers={"Ping": lambda c: c.count("count")})
        cfg = EngineConfig(n_nodes=3, outbox_cap=4, queue_cap=16,
                           t_limit_us=1_000_000)
        eng = DeviceEngine(CompiledActor(spec), cfg)
        eng.run(eng.init(np.arange(2)), max_steps=1)


def test_one_draw_per_transition_rule():
    spec = _ping_spec()

    def greedy(c):
        c.uniform(0, 10)
        c.uniform(0, 10)  # the second draw violates the static rule

    spec = dataclasses.replace(spec, handlers={"Ping": greedy})
    cfg = EngineConfig(n_nodes=3, outbox_cap=4, queue_cap=16,
                       t_limit_us=1_000_000)
    with pytest.raises(SpecError, match="at most\\s+once per event"):
        eng = DeviceEngine(CompiledActor(spec), cfg)
        eng.run(eng.init(np.arange(2)), max_steps=1)


# ---------------------------------------------------------------------------
# Lowering contracts.
# ---------------------------------------------------------------------------
def test_lane_dtype_from_declared_ranges():
    assert lane_dtype(Lane("a", hi=100), PACKED) == jnp.int8
    assert lane_dtype(Lane("a", hi=30_000), PACKED) == jnp.int16
    assert lane_dtype(Lane("a", hi=100_000), PACKED) == jnp.int32
    assert lane_dtype(Lane("a", hi=100, lo=-200), PACKED) == jnp.int16
    assert lane_dtype(Lane("a", hi=100, kind="bitmask"),
                      PACKED) == jnp.int32
    # The wide profile degrades every category to the i32 reference.
    assert lane_dtype(Lane("a", hi=100), WIDE) == jnp.int32


def test_generated_kind_names_render_in_traces():
    from madsim_tpu.actorc.families.paxos import (PaxosActor,
                                                  PaxosConfig,
                                                  engine_config)

    actor = PaxosActor(PaxosConfig())
    assert actor.kind_names == ["Cmd", "Prepare", "Promise", "Accept",
                                "Accepted", "Chosen", "Retry"]
    eng = DeviceEngine(actor, engine_config(PaxosConfig()))
    trace = eng.trace(3, max_steps=300)
    kinds = {e["kind"] for e in trace}
    assert "Prepare" in kinds and "Promise" in kinds \
        and "Chosen" in kinds, kinds


def test_restart_annotations_reset_volatile_lanes():
    """durable=False lanes lose the restarting node's row; durable
    lanes survive — the disk-vs-memory contract, checked end to end on
    both backends via the pb family's ack bookkeeping (volatile) vs
    log (durable) under a kill/restart schedule (the crosscheck above)
    and here directly on a tiny spec."""
    def h(c):
        c.write("mem", 7)
        c.write("disk", 9)

    spec = ActorSpec(
        name="vol", n_nodes=2,
        lanes=(Lane("mem", hi=10, durable=False, reset=3),
               Lane("disk", hi=10)),
        messages=(Message("Hit", ()),),
        handlers={"Hit": h},
        init=lambda c: c.event("Hit", time=10, dst=0),
        invariant=lambda v: v.np.asarray(False),
    )
    host = HostActor(spec, payload_words=2)
    s = host.init_state()
    s, _, _ = host.handle(s, kind=0, dst=0, payload=[], now=10)
    assert s["mem"][0] == 7 and s["disk"][0] == 9
    s2, _ = host.on_restart(s, node=0, now=20)
    assert s2["mem"][0] == 3, "volatile lane must reset to its reset value"
    assert s2["disk"][0] == 9, "durable lane must survive the restart"


# ---------------------------------------------------------------------------
# The Paxos family.
# ---------------------------------------------------------------------------
def test_paxos_clean_is_safe_and_decides():
    from madsim_tpu.actorc.families.paxos import (PaxosActor,
                                                  PaxosConfig,
                                                  engine_config)

    xcfg = PaxosConfig(contend_all=True)
    eng = DeviceEngine(PaxosActor(xcfg), engine_config(xcfg))
    obs = eng.observe(eng.run(eng.init(np.arange(256)), max_steps=6000))
    assert not obs["bug"].any()
    assert not obs["overflow"].any()
    assert (obs["slots_decided"] == xcfg.n_slots).all(), \
        "every contended decree must still decide on a clean network"


def test_paxos_forgetful_acceptor_violates_under_window_restarts():
    from madsim_tpu.actorc.families.paxos import (PaxosActor,
                                                  PaxosConfig,
                                                  engine_config)

    xcfg = PaxosConfig(buggy_forgetful_acceptor=True, contend_all=True)
    eng = DeviceEngine(PaxosActor(xcfg), engine_config(xcfg))
    # Two restarts inside the amnesia window of a contended decree
    # (tuning measurements in actorc/families/paxos.py).
    faults = np.array([[80_000, FAULT_RESTART, 0, 0],
                       [83_000, FAULT_RESTART, 2, 0]], np.int32)
    obs = eng.observe(eng.run(eng.init(np.arange(512), faults=faults),
                              max_steps=8000))
    assert obs["bug"].any(), "amnesia restarts must split a decree"
    assert not obs["bug"].all(), "only some interleavings race"
    # The SAME schedule against durable acceptors stays safe: the bug
    # is the flipped annotation, not the schedule.
    good = PaxosConfig(contend_all=True)
    geng = DeviceEngine(PaxosActor(good), engine_config(good))
    gobs = geng.observe(geng.run(geng.init(np.arange(512),
                                           faults=faults),
                                 max_steps=8000))
    assert not gobs["bug"].any()


def test_compiled_actor_state_is_dict_of_declared_lanes():
    spec = _ping_spec()
    cfg = EngineConfig(n_nodes=3, outbox_cap=4, queue_cap=16,
                       t_limit_us=1_000_000)
    eng = DeviceEngine(CompiledActor(spec), cfg)
    state = eng.init(np.arange(4))
    assert set(state.astate) == {"count"}
    assert state.astate["count"].dtype == jnp.int8  # hi=100 -> code lane
    final = eng.run(state, max_steps=200)
    assert (np.asarray(final.astate["count"]).sum(axis=-1) >= 6).all()
