"""Service-layer ergonomics: @service/@rpc_method, tracing spans, examples.

Reference analogs: `madsim-macros/src/service.rs:8-111` (the service macro)
and `madsim/src/sim/task.rs:58-82` (per-node/per-task tracing spans).
"""
import dataclasses
import logging
import re
import subprocess
import sys
from pathlib import Path

import pytest

import madsim_tpu as ms
from madsim_tpu import time as vtime
from madsim_tpu.core.runtime import sim_span
from madsim_tpu.net import Endpoint, rpc, rpc_method, service


@dataclasses.dataclass
class Put:
    key: str
    value: str


@dataclasses.dataclass
class Get:
    key: str


@service
class KvStore:
    def __init__(self):
        self.data = {}

    @rpc_method
    async def put(self, req: Put) -> str:
        self.data[req.key] = req.value
        return "ok"

    @rpc_method
    async def get(self, req: Get) -> "str | None":
        return self.data.get(req.key)

    async def not_an_rpc(self, whatever):
        raise AssertionError("never registered")


def test_service_decorator_registers_annotated_methods():
    assert KvStore.__rpc_methods__ == {"put": Put, "get": Get}

    async def main():
        h = ms.Handle.current()
        store = KvStore()

        async def server():
            await store.serve("10.0.0.1:700")
            await vtime.sleep(600)

        h.create_node(name="kv", ip="10.0.0.1", init=server)
        cli = h.create_node(name="cli", ip="10.0.0.2")

        async def client():
            ep = await Endpoint.bind("0.0.0.0:0")
            assert await rpc.call(ep, "10.0.0.1:700",
                                  Put("k", "v"), timeout=5.0) == "ok"
            assert await rpc.call(ep, "10.0.0.1:700",
                                  Get("k"), timeout=5.0) == "v"
            assert await rpc.call(ep, "10.0.0.1:700",
                                  Get("nope"), timeout=5.0) is None
            return True

        return await cli.spawn(client())

    assert ms.run(main(), seed=1, time_limit=60)


def test_rpc_method_requires_annotation():
    with pytest.raises(TypeError, match="annotated"):
        @service
        class Bad:
            @rpc_method
            async def handler(self, req):
                return req

    with pytest.raises(TypeError, match="async"):
        @rpc_method
        def sync_handler(self, req: Put):
            return req


def test_sim_span_carries_node_task_and_vtime():
    async def main():
        h = ms.Handle.current()
        node = h.create_node(name="worker", ip="10.0.0.5")
        box = []

        async def body():
            await vtime.sleep(0.5)
            box.append(sim_span())

        await node.spawn(body())
        return box[0]

    span = ms.run(main(), seed=2)
    assert "node=1/worker" in span
    assert "task=" in span
    assert "t=0.5" in span
    assert sim_span() == ""  # outside any simulation


def test_log_records_carry_span():
    # Capture through a handler wearing the real _SpanFilter: the filter
    # runs at emit time, INSIDE the simulation, so the captured span must
    # carry the emitting node/task/vtime.
    from madsim_tpu.core.runtime import _SpanFilter

    spans = []

    class Capture(logging.Handler):
        def emit(self, record):
            spans.append(record.sim)

    handler = Capture()
    handler.addFilter(_SpanFilter())
    logger = logging.getLogger("spantest")
    logger.addHandler(handler)
    try:
        async def main():
            h = ms.Handle.current()
            node = h.create_node(name="svc", ip="10.0.0.3")

            async def body():
                await vtime.sleep(0.25)
                logger.warning("hello from the sim")

            await node.spawn(body())

        ms.run(main(), seed=3)
    finally:
        logger.removeHandler(handler)
    assert len(spans) == 1
    assert "node=1/svc" in spans[0]
    assert "task=" in spans[0] and "t=0.25" in spans[0]
    # Outside a sim the same filter injects an empty span, not garbage.
    logger.addHandler(handler)
    try:
        logger.warning("outside")
    finally:
        logger.removeHandler(handler)
    assert spans[-1] == ""


def test_service_rejects_duplicate_request_types():
    with pytest.raises(TypeError, match="exactly one handler"):
        @service
        class Dup:
            @rpc_method
            async def a(self, req: Put) -> str:
                return "a"

            @rpc_method
            async def b(self, req: Put) -> str:
                return "b"


def test_service_inherits_base_rpc_methods():
    @service
    class Extended(KvStore):
        @rpc_method
        async def both(self, req: "Swap") -> str:
            return "swapped"

    assert set(Extended.__rpc_methods__) == {"put", "get", "both"}


@dataclasses.dataclass
class Swap:
    a: str
    b: str


def test_greeter_example_runs_deterministically():
    example = Path(__file__).resolve().parent.parent / "examples" / "greeter.py"

    def run(seed):
        proc = subprocess.run(
            [sys.executable, str(example)],
            env={"PATH": "/usr/bin:/bin:/usr/local/bin",
                 "MADSIM_TEST_SEED": str(seed)},
            capture_output=True, text=True, timeout=120)
        assert proc.returncode == 0, proc.stderr[-500:]
        return proc.stdout

    a = run(5)
    b = run(5)
    c = run(6)
    assert "world done" in a
    assert a == b, "same-seed example runs must be bit-identical"
    assert a != c


def test_kv_store_example_finds_missing_fsync():
    example = Path(__file__).resolve().parent.parent / "examples" / "kv_store.py"
    env = {"PATH": "/usr/bin:/bin:/usr/local/bin",
           "MADSIM_TEST_SEED": "0", "MADSIM_TEST_NUM": "8"}

    clean = subprocess.run([sys.executable, str(example)], env=env,
                           capture_output=True, text=True, timeout=180)
    assert clean.returncode == 0, clean.stdout + clean.stderr[-500:]
    assert "DURABILITY BUG" not in clean.stdout

    buggy = subprocess.run([sys.executable, str(example), "--buggy"], env=env,
                           capture_output=True, text=True, timeout=180)
    assert buggy.returncode == 0, buggy.stdout + buggy.stderr[-500:]
    assert "DURABILITY BUG" in buggy.stdout
    assert "MADSIM_TEST_SEED=" in buggy.stdout  # repro line

    # The failing seed reproduces in isolation: same seed, same bug.
    m = re.search(r"MADSIM_TEST_SEED=(\d+)", buggy.stdout)
    repro = subprocess.run(
        [sys.executable, str(example), "--buggy"],
        env={**env, "MADSIM_TEST_SEED": m.group(1), "MADSIM_TEST_NUM": "1"},
        capture_output=True, text=True, timeout=120)
    assert "DURABILITY BUG" in repro.stdout
