"""speclint (pass 4) tests: seeded-defect golden fixtures, the compile
gate, suppression mechanics (pragmas, lint_allow, ignore/terminal
hygiene), the shipped-family cleanliness invariant, and protocol-card
byte-stability."""
import dataclasses
import importlib.util
import os

import pytest

from madsim_tpu.analysis import scan_source
from madsim_tpu.analysis.speclint import (gate_spec, lint_spec,
                                          protocol_card, run_spec_pass,
                                          shipped_specs)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "fixtures", "speclint")

# fixture -> {rule code: expected finding count} (golden findings).
GOLDEN = {
    "clean": {},
    "bad_unreachable": {"SPC010": 1},
    "bad_unhandled": {"SPC011": 1},
    "bad_noop": {"SPC012": 1},
    "bad_timer": {"SPC010": 1, "SPC020": 1, "SPC021": 1},
    "bad_capacity": {"SPC030": 1, "SPC031": 1},
    "bad_effects": {"SPC040": 1, "SPC041": 1},
    "bad_durability": {"SPC050": 1},
    "stale_pragma": {"DET900": 1},
}

# (fixture, rule) -> substrings the finding must name: the offending
# state / message / word, plus the diagnosis — pointed, not generic.
POINTED = {
    ("bad_unreachable", "SPC010"): ("'Lost'", "unreachable"),
    ("bad_unhandled", "SPC011"): ("'Drop'", "no handler"),
    ("bad_noop", "SPC012"): ("'Pong'", "no effects"),
    ("bad_timer", "SPC020"): ("'Dead'", "never armed"),
    ("bad_timer", "SPC021"): ("'Tick'", "disjoint"),
    ("bad_capacity", "SPC030"): ("'small'", "[100, 200]"),
    ("bad_capacity", "SPC031"): ("'x'", "[50, 150]"),
    ("bad_effects", "SPC040"): ("'Pong'", "disjoint"),
    ("bad_effects", "SPC041"): ("at most once", "'Pong'"),
    ("bad_durability", "SPC050"): ("'mem'", "on_restart"),
    ("stale_pragma", "DET900"): ("SPC030",),
}


def _load(name, path=None):
    """Import a fixture module fresh (closures and co_filename intact)."""
    path = path or os.path.join(FIXTURES, name + ".py")
    mspec = importlib.util.spec_from_file_location(
        f"speclint_fixture_{name}", path)
    mod = importlib.util.module_from_spec(mspec)
    mspec.loader.exec_module(mod)
    return mod


def _build(name):
    return _load(name).build()


@pytest.mark.parametrize("fixture,expected", sorted(GOLDEN.items()))
def test_golden_fixture_findings(fixture, expected):
    findings = lint_spec(_build(fixture), root=REPO)
    counts = {}
    for f in findings:
        counts[f.rule] = counts.get(f.rule, 0) + 1
    assert counts == expected, "\n".join(f.render() for f in findings)
    rel = f"tests/fixtures/speclint/{fixture}.py"
    for f in findings:
        assert f.path == rel and f.line > 0, f.render()


@pytest.mark.parametrize("fixture,rule", sorted(POINTED))
def test_findings_name_the_offender(fixture, rule):
    findings = [f for f in lint_spec(_build(fixture), root=REPO)
                if f.rule == rule]
    assert findings, f"{fixture} produced no {rule}"
    for needle in POINTED[(fixture, rule)]:
        assert any(needle in f.message for f in findings), \
            f"{rule} message lacks {needle!r}: " + \
            "\n".join(f.render() for f in findings)


# -- the compile gate -------------------------------------------------------

def test_compile_gate_rejects_dsl_gap_specs():
    """The acceptance bar: a spec leaning on a known DSL gap
    (per-destination payloads, multi-timer arms, >1 RNG draw) is
    rejected with an SPC diagnostic instead of silently miscompiling."""
    from madsim_tpu.actorc.compile import CompiledActor
    from madsim_tpu.actorc.spec import SpecError

    for fixture, code in (("bad_effects", "SPC040"),   # per-dst payloads
                          ("bad_effects", "SPC041"),   # >1 RNG draw
                          ("bad_timer", "SPC021"),     # multi-timer arms
                          ("bad_capacity", "SPC030")):
        with pytest.raises(SpecError) as ei:
            CompiledActor(_build(fixture))
        assert "speclint" in str(ei.value) and code in str(ei.value)


def test_compile_gate_passes_clean_spec_and_buggy_shipped_variants():
    from madsim_tpu.actorc.compile import CompiledActor
    from madsim_tpu.actorc.families.paxos import PaxosConfig, paxos_spec
    from madsim_tpu.actorc.families.pb import pb_spec
    from madsim_tpu.actorc.families.tpc import tpc_spec
    from madsim_tpu.engine.pb_actor import PBDeviceConfig
    from madsim_tpu.engine.tpc_actor import TPCDeviceConfig

    CompiledActor(_build("clean"))
    # The deliberately-buggy experiment configs still compile: the
    # injected protocol bugs are dynamic (schedule-gated), not spec
    # malformations — except the forgetful acceptor, whose lint_allow
    # carries its intentional SPC050.
    CompiledActor(paxos_spec(PaxosConfig(buggy_forgetful_acceptor=True)))
    CompiledActor(pb_spec(PBDeviceConfig(buggy_commit_early=True)))
    CompiledActor(tpc_spec(TPCDeviceConfig(buggy_presumed_commit=True)))


# -- the tier-1 invariant ---------------------------------------------------

def test_shipped_families_are_speclint_clean():
    """Pass 4 over every shipped family spec finds nothing — the same
    invariant `make lint` and CI enforce. A regression here means a
    spec edit introduced dead protocol, a capacity hole or an effect-
    budget violation."""
    findings = run_spec_pass(root=REPO)
    assert findings == [], "\n".join(f.render() for f in findings)


# -- suppression mechanics --------------------------------------------------

def test_pragma_suppresses_spc_finding_on_its_line(tmp_path):
    src = open(os.path.join(FIXTURES, "bad_capacity.py"),
               encoding="utf-8").read()
    anchor = 'c.write("small", c.read("small") + 100, when=live)'
    assert anchor in src
    p = tmp_path / "pragma_capacity.py"
    p.write_text(src.replace(
        anchor, anchor + "  # detlint: allow[SPC030]"))
    spec = _load("pragma_capacity", str(p)).build()
    rules = [f.rule for f in lint_spec(spec, root=str(tmp_path))]
    assert rules == ["SPC031"]  # SPC030 suppressed, pragma not stale


def test_stale_spc_pragma_is_owned_by_pass4_not_pass1():
    spec = _build("stale_pragma")
    (f,) = lint_spec(spec, root=REPO)
    assert f.rule == "DET900" and "SPC030" in f.message
    # Pass 1 scans the same file and must NOT claim the SPC pragma:
    # each pass owns its own rule prefixes (no double DET900s).
    src = open(os.path.join(FIXTURES, "stale_pragma.py"),
               encoding="utf-8").read()
    assert scan_source(src, "stale_pragma.py") == []


def test_lint_allow_suppresses_per_code_and_star_waives_pass():
    allowed = dataclasses.replace(_build("bad_durability"),
                                  lint_allow=("SPC050",))
    assert lint_spec(allowed, root=REPO) == []
    star = dataclasses.replace(_build("bad_timer"), lint_allow=("*",))
    assert lint_spec(star, root=REPO) == []


def test_stale_lint_allow_is_spc900():
    spec = dataclasses.replace(_build("clean"), lint_allow=("SPC030",))
    (f,) = lint_spec(spec, root=REPO)
    assert f.rule == "SPC900" and "SPC030" in f.message


def test_ignore_declares_a_kind_unhandled_on_purpose():
    spec = _build("bad_unhandled")
    assert lint_spec(dataclasses.replace(spec, ignore=("Drop",)),
                     root=REPO) == []
    fs = lint_spec(dataclasses.replace(spec, ignore=("Drop", "Nope")),
                   root=REPO)
    assert [f.rule for f in fs] == ["SPC013"]
    assert "'Nope'" in fs[0].message


def test_handled_and_ignored_is_spc013():
    fs = lint_spec(dataclasses.replace(_build("clean"), ignore=("Pong",)),
                   root=REPO)
    assert [f.rule for f in fs] == ["SPC013"]
    assert "'Pong'" in fs[0].message and "both handled" in fs[0].message


def test_terminal_kind_that_emits_is_spc013():
    fs = lint_spec(dataclasses.replace(_build("clean"),
                                       terminal=("Ping",)),
                   root=REPO)
    assert [f.rule for f in fs] == ["SPC013"]
    assert "'Ping'" in fs[0].message and "terminal" in fs[0].message


# -- protocol cards ---------------------------------------------------------

def test_protocol_card_is_byte_stable():
    """Two independent renders are identical — the CI demo diffs them."""
    a = protocol_card(shipped_specs()["paxos"])
    b = protocol_card(shipped_specs()["paxos"])
    assert a == b
    assert a.startswith("protocol card: paxos")
    for section in ("kinds x handlers", "timer graph", "lane budgets",
                    "init seeds:"):
        assert section in a


def test_protocol_card_surfaces_protocol_shape():
    card = protocol_card(_build("bad_unhandled"))
    assert "UNHANDLED" in card and "Drop" in card
    card = protocol_card(_build("clean"))
    assert "handled" in card and "UNHANDLED" not in card
    # the lane budget row carries the declared range, dtype and the
    # abstract max-write bound
    assert "[0, 100]" in card and "i8" in card
