"""Fleet fabric (madsim_tpu/fleet, docs/fleet.md): leased seed ranges,
crash-identical recovery, duplicate-completion crosschecks.

The headline contract (ISSUE 7 acceptance): a 2+-worker fleet sweep
with injected worker kills, lease expiries, duplicated completions,
SIGTERM preemptions, and torn checkpoints returns a SweepResult whose
CONTRACT fields — seed ids, bug flags, per-seed observations (incl. the
``m_*`` metrics frames), coverage ledger hits/first-seen — are bitwise
identical to BOTH a crash-free fleet run and a single-host ``sweep()``
over the same seeds, for raft/pb/tpc. Fabric telemetry (histories,
loop_stats) legitimately differs and is excluded.
"""
import json

import numpy as np
import pytest

from madsim_tpu.engine import (
    DeviceEngine,
    EngineConfig,
    PBActor,
    PBDeviceConfig,
    RaftActor,
    RaftDeviceConfig,
    TPCActor,
    TPCDeviceConfig,
)
from madsim_tpu.fleet import (
    ChaosConfig,
    Coordinator,
    FleetIntegrityError,
    LeaseTable,
    RetryPolicy,
    SeedRange,
    VirtualClock,
    fleet_sweep,
    split_ranges,
)
from madsim_tpu.parallel.sweep import sweep

RCFG = RaftDeviceConfig(n=3, buggy_double_vote=True)
ECFG = EngineConfig(n_nodes=3, outbox_cap=4, queue_cap=64,
                    t_limit_us=1_500_000, stop_on_bug=True)
SWEEP_KW = dict(chunk_steps=64, max_steps=20_000)

# The full failure mix in one config: explicit kill, preemption, lease
# expiry via the kill, duplicated completions, transient RPC failures.
CHAOS = ChaosConfig(seed=11, kill_at=(("w0", 2),),
                    preempt_at=(("w1", 5),),
                    duplicate_all_completions=True,
                    drop_rpc_rate=0.25, drop_heartbeat_rate=0.1,
                    restart_after=2)


@pytest.fixture(scope="module")
def raft_eng():
    # metrics=True so the acceptance check covers the coverage ledger
    # and the per-seed m_* metrics frames too.
    import dataclasses

    return DeviceEngine(RaftActor(RCFG),
                        dataclasses.replace(ECFG, metrics=True))


RAFT_SEEDS = np.arange(64)


@pytest.fixture(scope="module")
def raft_single(raft_eng):
    """Single-host reference over RAFT_SEEDS — computed once; every
    fleet leg in this module compares against the same run."""
    return sweep(None, raft_eng.cfg, RAFT_SEEDS, engine=raft_eng,
                 **SWEEP_KW)


def assert_contract_equal(a, b):
    """The crash-identical contract: ids, bug flags, observations
    (metrics frames included), coverage ledger."""
    np.testing.assert_array_equal(a.seeds, b.seeds)
    np.testing.assert_array_equal(a.bug, b.bug)
    assert set(a.observations) == set(b.observations)
    for k in a.observations:
        np.testing.assert_array_equal(a.observations[k], b.observations[k],
                                      err_msg=k)
    assert a.failing_seeds == b.failing_seeds
    assert (a.coverage is None) == (b.coverage is None)
    if a.coverage is not None:
        np.testing.assert_array_equal(a.coverage.hits, b.coverage.hits)
        np.testing.assert_array_equal(a.coverage.first_seen_seed,
                                      b.coverage.first_seen_seed)
        assert a.coverage.distinct_behaviors == b.coverage.distinct_behaviors


# ---------------------------------------------------------------------------
# Protocol units (no device work)
# ---------------------------------------------------------------------------

def test_split_ranges_tiles_and_is_deterministic():
    rs = split_ranges(100, 32)
    assert [r.range_id for r in rs] == [0, 1, 2, 3]
    assert rs[0].lo == 0 and rs[-1].hi == 100
    assert sum(r.n_seeds for r in rs) == 100
    assert split_ranges(100, 32) == rs  # pure function of the inputs
    with pytest.raises(ValueError):
        split_ranges(10, 0)


def test_lease_table_expiry_reissue_and_dedup():
    table = LeaseTable(split_ranges(8, 4), ttl=5)
    a = table.issue("w0", now=0)
    b = table.issue("w1", now=0)
    assert a.range.range_id == 0 and b.range.range_id == 1
    assert table.issue("w0", now=0) is None  # nothing pending
    # Heartbeat extends; a stale lease id is refused.
    assert table.heartbeat(a.lease_id, "w0", now=3)
    assert not table.heartbeat(999, "w0", now=3)
    assert not table.heartbeat(a.lease_id, "w1", now=3)  # wrong holder
    # w1 never heartbeats: its lease expires and the range re-queues.
    reaped = table.expire(now=6)
    assert [l.range.range_id for l in reaped] == [1]
    c = table.issue("w0", now=6)
    assert c.range.range_id == 1 and c.generation == 1
    # The ORIGINAL holder completes anyway: accepted (first), and the
    # re-issued holder's later completion resolves as a duplicate.
    first, _ = table.complete(1, b.lease_id)
    assert first
    dup, _ = table.complete(1, c.lease_id)
    assert not dup
    # Voluntary release re-queues immediately with the checkpoint.
    assert table.release(a.lease_id, "w0", checkpoint="/tmp/ck.npz")
    d = table.issue("w1", now=7)
    assert d.range.range_id == 0 and d.checkpoint == "/tmp/ck.npz"


def test_retry_backoff_is_deterministic_and_jittered():
    p = RetryPolicy(seed=3, base_delay=1.0, jitter=0.5)
    q = RetryPolicy(seed=3, base_delay=1.0, jitter=0.5)
    d = [p.delay("w0:acquire", a) for a in range(4)]
    assert d == [q.delay("w0:acquire", a) for a in range(4)]  # replayable
    assert d[1] > d[0] and d[2] > d[1]  # exponential growth survives jitter
    assert d != [p.delay("w1:acquire", a) for a in range(4)]  # desynced


def test_call_with_retry_exhaustion_and_success():
    from madsim_tpu.fleet import RetryExhausted, RpcError, call_with_retry

    clock = VirtualClock()
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise RpcError("boom")
        return "ok"

    assert call_with_retry(flaky, RetryPolicy(max_attempts=5), clock,
                           "t") == "ok"
    assert calls["n"] == 3
    assert clock.now() > 0  # backoff advanced the fabric clock
    with pytest.raises(RetryExhausted):
        call_with_retry(lambda: (_ for _ in ()).throw(RpcError("x")),
                        RetryPolicy(max_attempts=2), clock, "t2")


def _fake_result(seeds, bug_at=()):
    obs = {"bug": np.isin(np.arange(len(seeds)), bug_at),
           "steps": np.ones(len(seeds), np.int32)}
    from madsim_tpu.parallel.sweep import SweepResult

    return SweepResult(seeds=np.asarray(seeds, np.uint64), bug=obs["bug"],
                       observations=obs, steps_run=1, n_devices=1)


def test_duplicate_mismatch_raises_integrity_error():
    """A double-reported range whose two executions disagree bitwise is
    the one unrecoverable fleet fault: nondeterminism. It must raise,
    never silently pick a winner."""
    clock = VirtualClock()
    coord = Coordinator(np.arange(8), range_size=8, lease_ttl=10,
                        clock=clock)
    lease = coord.rpc_acquire(worker_id="w0")
    ok = _fake_result(np.arange(8))
    coord.rpc_complete(worker_id="w0", lease_id=lease["lease_id"],
                       range_id=0, result=ok)
    # Identical duplicate: crosschecked and absorbed.
    out = coord.rpc_complete(worker_id="w1", lease_id=lease["lease_id"],
                             range_id=0, result=_fake_result(np.arange(8)))
    assert out["duplicate"]
    assert coord.stats["duplicates_crosschecked"] == 1
    with pytest.raises(FleetIntegrityError, match="bitwise"):
        coord.rpc_complete(worker_id="w1", lease_id=lease["lease_id"],
                           range_id=0,
                           result=_fake_result(np.arange(8), bug_at=(3,)))


def test_merge_requires_tiling_ranges():
    from madsim_tpu.fleet import merge_range_results

    with pytest.raises(ValueError, match="not completed"):
        merge_range_results(np.arange(8), [SeedRange(0, 0, 8)], {}, 1)


# ---------------------------------------------------------------------------
# The chaos matrix (the tier-1 acceptance contract)
# ---------------------------------------------------------------------------

def test_chaos_matrix_raft(raft_eng, raft_single, tmp_path):
    """Raft (with coverage + metrics): single-host == clean fleet ==
    chaotic fleet, with every failure mode injected at once — and the
    chaos demonstrably happened (kills, expiries, duplicates, retries,
    preemption all nonzero)."""
    single = raft_single
    clean = fleet_sweep(None, raft_eng.cfg, RAFT_SEEDS, engine=raft_eng,
                        n_workers=2, range_size=16, **SWEEP_KW)
    chaotic = fleet_sweep(None, raft_eng.cfg, RAFT_SEEDS, engine=raft_eng,
                          n_workers=2, range_size=16, chaos=CHAOS,
                          checkpoint_dir=str(tmp_path / "ck"),
                          **SWEEP_KW)
    assert_contract_equal(single, clean)
    assert_contract_equal(single, chaotic)
    assert single.failing_seeds, "matrix must exercise failing seeds"
    fleet_stats = chaotic.loop_stats["fleet"]
    assert fleet_stats["kills"] >= 1
    assert fleet_stats["preemptions"] >= 1
    assert fleet_stats["leases_expired"] >= 1
    assert fleet_stats["leases_reissued"] >= 1
    assert fleet_stats["duplicate_completions"] >= 1
    assert fleet_stats["duplicates_crosschecked"] == \
        fleet_stats["duplicate_completions"]
    assert fleet_stats["rpc_retries"] >= 1


def test_chaos_matrix_pb():
    eng = DeviceEngine(
        PBActor(PBDeviceConfig(n=3, n_writes=4)),
        EngineConfig(n_nodes=3, outbox_cap=4, queue_cap=64,
                     t_limit_us=1_500_000, loss_rate=0.05))
    seeds = np.arange(32)
    single = sweep(None, eng.cfg, seeds, engine=eng, **SWEEP_KW)
    clean = fleet_sweep(None, eng.cfg, seeds, engine=eng, n_workers=2,
                        range_size=8, **SWEEP_KW)
    chaotic = fleet_sweep(None, eng.cfg, seeds, engine=eng, n_workers=2,
                          range_size=8, chaos=CHAOS, **SWEEP_KW)
    assert_contract_equal(single, clean)
    assert_contract_equal(single, chaotic)
    assert chaotic.loop_stats["fleet"]["kills"] >= 1


def test_chaos_matrix_tpc():
    eng = DeviceEngine(
        TPCActor(TPCDeviceConfig(n=4, n_txns=4, buggy_presumed_commit=True)),
        EngineConfig(n_nodes=4, outbox_cap=5, queue_cap=64,
                     t_limit_us=1_500_000, loss_rate=0.1))
    seeds = np.arange(32)
    single = sweep(None, eng.cfg, seeds, engine=eng, **SWEEP_KW)
    clean = fleet_sweep(None, eng.cfg, seeds, engine=eng, n_workers=2,
                        range_size=8, **SWEEP_KW)
    chaotic = fleet_sweep(None, eng.cfg, seeds, engine=eng, n_workers=2,
                          range_size=8, chaos=CHAOS, **SWEEP_KW)
    assert_contract_equal(single, clean)
    assert_contract_equal(single, chaotic)
    assert single.failing_seeds  # buggy config: bug attribution survives


def test_fleet_composes_with_multihost_mesh(raft_eng, raft_single):
    """The DCN×ICI leg: every worker sweeps its leases on the 2-D
    multihost mesh (psum over dcn+worlds inside each lease) and the
    merged result still equals the single-host reference."""
    from madsim_tpu.parallel.mesh import multihost_mesh

    single = raft_single
    mesh2d = multihost_mesh(n_hosts=2)
    assert mesh2d.devices.shape == (2, 4)
    fleet = fleet_sweep(None, raft_eng.cfg, RAFT_SEEDS, engine=raft_eng,
                        mesh=mesh2d, n_workers=2, range_size=16,
                        chaos=ChaosConfig(seed=5, kill_at=(("w1", 3),),
                                          restart_after=1),
                        **SWEEP_KW)
    assert_contract_equal(single, fleet)
    assert fleet.loop_stats["fleet"]["kills"] == 1


# ---------------------------------------------------------------------------
# Preemption + checkpoint recovery
# ---------------------------------------------------------------------------

def test_preemption_releases_lease_and_resumes_checkpoint(raft_eng,
                                                          raft_single,
                                                          tmp_path):
    """SIGTERM path: the preempted worker's lease re-queues immediately
    with its checkpoint attached; the next holder RESUMES (bit-exactly)
    instead of replaying, and the result is still contract-identical."""
    single = raft_single
    recs = []
    fleet = fleet_sweep(
        None, raft_eng.cfg, RAFT_SEEDS, engine=raft_eng, n_workers=2,
        range_size=32, observe=recs.append,
        checkpoint_dir=str(tmp_path / "ck"), checkpoint_every_chunks=1,
        chaos=ChaosConfig(seed=2, preempt_at=(("w0", 2),),
                          restart_after=2),
        **SWEEP_KW)
    assert_contract_equal(single, fleet)
    stats = fleet.loop_stats["fleet"]
    assert stats["preemptions"] >= 1
    assert stats["checkpoints_recovered"] >= 1
    events = [r["event"] for r in recs]
    assert "worker_preempted" in events
    assert "lease_released" in events
    assert "lease_resumed" in events
    rel = next(r for r in recs if r["event"] == "worker_preempted")
    assert rel["checkpoint"], "preemption must release WITH a checkpoint"


def test_torn_checkpoint_recovers_by_rerun(raft_eng, raft_single,
                                           tmp_path):
    """Crash-corrupted checkpoint: the killed worker's file is torn; the
    next holder's resume hits the hardened loader's CheckpointError,
    discards the file, re-runs fresh — same bitwise result."""
    single = raft_single
    recs = []
    fleet = fleet_sweep(
        None, raft_eng.cfg, RAFT_SEEDS, engine=raft_eng, n_workers=2,
        range_size=32, observe=recs.append,
        checkpoint_dir=str(tmp_path / "ck"), checkpoint_every_chunks=1,
        chaos=ChaosConfig(seed=4, kill_at=(("w0", 3),),
                          tear_checkpoint_on_kill=True, restart_after=2),
        **SWEEP_KW)
    assert_contract_equal(single, fleet)
    stats = fleet.loop_stats["fleet"]
    assert stats["kills"] >= 1
    assert stats["checkpoints_discarded"] >= 1
    events = [r["event"] for r in recs]
    assert "checkpoint_torn" in events
    assert "checkpoint_corrupt" in events


# ---------------------------------------------------------------------------
# Telemetry stream
# ---------------------------------------------------------------------------

def test_fleet_telemetry_jsonl_and_watch(raft_eng, tmp_path):
    """The observatory stream gains per-worker lease/retry/re-lease
    records: JSONL sink, schema'd records, and `obs watch` renders a
    fleet summary."""
    import io

    from madsim_tpu.obs.observatory import watch

    seeds = np.arange(32)
    path = str(tmp_path / "fleet.jsonl")
    fleet_sweep(None, raft_eng.cfg, seeds, engine=raft_eng, n_workers=2,
                range_size=8, observe=path,
                chaos=ChaosConfig(seed=9, kill_at=(("w1", 2),),
                                  drop_rpc_rate=0.3, restart_after=1),
                **SWEEP_KW)
    with open(path) as f:
        recs = [json.loads(line) for line in f if line.strip()]
    assert recs, "stream must not be empty"
    assert all(r["schema"] == "madsim.fleet.telemetry/1" for r in recs)
    events = {r["event"] for r in recs}
    assert {"lease_issued", "heartbeat", "completion",
            "fleet_summary"} <= events
    assert "worker_killed" in events and "lease_expired" in events
    assert "rpc_retry" in events
    # Re-issued lease records carry the generation + reissued flag.
    reissues = [r for r in recs
                if r["event"] == "lease_issued" and r.get("reissued")]
    assert reissues and all(r["generation"] >= 1 for r in reissues)
    out = io.StringIO()
    assert watch(path, out=out) == 0
    text = out.getvalue()
    assert "fleet:" in text and "crosschecked" in text


def test_fleet_stalls_loudly_when_unrecoverable(raft_eng):
    """All workers dead + restarts disabled must raise FleetStalledError
    — and its message must name each stuck range with its holding
    worker, lease generation, and last-heartbeat bookkeeping (the PR 12
    satellite: diagnostics, not a bare range count). Under the default
    lease prefetch BOTH of the dead worker's leases are outstanding —
    the report must name the running one AND the prefetched one, with
    the prefetched lease annotated as queued behind the running lease
    (a prefetched lease must not read as a hung sweep)."""
    from madsim_tpu.fleet import FleetStalledError

    with pytest.raises(FleetStalledError, match="dead") as exc:
        fleet_sweep(None, raft_eng.cfg, np.arange(16), engine=raft_eng,
                    n_workers=1, range_size=8,
                    chaos=ChaosConfig(seed=1, kill_at=(("w0", 1),),
                                      restart_after=-1),
                    **SWEEP_KW)
    msg = str(exc.value)
    assert "range 0: held by w0" in msg
    assert "last heartbeat" in msg and "heartbeats" in msg
    assert "expires t=" in msg
    # The prefetched lease: held by the same worker, explicitly marked.
    assert "range 1: held by w0" in msg
    assert "prefetched behind lease 0" in msg


def test_fleet_stall_report_without_prefetch(raft_eng):
    """prefetch=0 restores the one-lease-per-quantum fabric: a stalled
    single-worker fleet holds only its running range; the other range
    is reported pending for re-issue."""
    from madsim_tpu.fleet import FleetStalledError

    with pytest.raises(FleetStalledError, match="dead") as exc:
        fleet_sweep(None, raft_eng.cfg, np.arange(16), engine=raft_eng,
                    n_workers=1, range_size=8, prefetch=0,
                    chaos=ChaosConfig(seed=1, kill_at=(("w0", 1),),
                                      restart_after=-1),
                    **SWEEP_KW)
    msg = str(exc.value)
    assert "range 0: held by w0" in msg
    assert "prefetched" not in msg
    assert "range 1: pending" in msg


# ---------------------------------------------------------------------------
# Fabric cost disciplines (ISSUE 17): persistent sessions, prefetch,
# coalesced control plane — counted, not vibes
# ---------------------------------------------------------------------------

def test_session_run_group_bitwise_equals_solo_sweeps(raft_eng):
    """The tentpole's correctness gate: every per-range result a
    SweepSession.run_group emits is bitwise interchangeable (contract
    fields) with a fresh solo ``sweep()`` of that range — including the
    SECOND group, which rides the session's recycled standing slots
    (``refill`` path) instead of a fresh device init."""
    from madsim_tpu.fleet.merge import contract_mismatches
    from madsim_tpu.parallel import SweepSession

    sess = SweepSession(engine=raft_eng, mesh=None, **SWEEP_KW)
    groups = [np.arange(48, dtype=np.uint64),
              np.arange(100, 148, dtype=np.uint64)]
    for gi, seeds in enumerate(groups):
        parts = [{"seeds": seeds[lo:lo + 16], "faults": None}
                 for lo in range(0, 48, 16)]
        results = sess.run_group(parts)
        assert len(results) == 3
        for part, res in zip(parts, results):
            solo = sweep(None, raft_eng.cfg, part["seeds"],
                         engine=raft_eng, **SWEEP_KW)
            assert contract_mismatches(solo, res) == []
            assert res.loop_stats["session_group"] == 3
            assert res.loop_stats["session_reused_slots"] == (gi > 0)
    # 6 leases rode the session; only the very first paid an install.
    assert sess.reuse_hits == 5


def test_session_grouped_adds_no_device_fetches(raft_eng, monkeypatch):
    """Counted discipline: a grouped session quantum performs NO more
    host pulls through the sanctioned ``_fetch`` hook than the same
    ranges swept solo (the grouped pipelined loop still pays ONE scalar
    fetch per superstep and one ledger pull — for the whole group
    instead of per range)."""
    import importlib

    from madsim_tpu.parallel import SweepSession

    # The package re-exports the sweep FUNCTION under the module's
    # name, so fetch the module object explicitly.
    sweep_mod = importlib.import_module("madsim_tpu.parallel.sweep")

    seeds = np.arange(200, 248, dtype=np.uint64)
    counter = {"n": 0}
    real_fetch = sweep_mod._fetch

    def counting_fetch(x):
        counter["n"] += 1
        return real_fetch(x)

    monkeypatch.setattr(sweep_mod, "_fetch", counting_fetch)
    solo_fetches = 0
    for lo in range(0, 48, 16):
        counter["n"] = 0
        sweep_mod.sweep(None, raft_eng.cfg, seeds[lo:lo + 16],
                        engine=raft_eng, **SWEEP_KW)
        solo_fetches += counter["n"]
    sess = SweepSession(engine=raft_eng, mesh=None, **SWEEP_KW)
    counter["n"] = 0
    sess.run_group([{"seeds": seeds[lo:lo + 16], "faults": None}
                    for lo in range(0, 48, 16)])
    grouped_fetches = counter["n"]
    assert grouped_fetches <= solo_fetches, \
        (f"grouped quantum pulled {grouped_fetches} times vs "
         f"{solo_fetches} solo — the session must not add device syncs")


def test_fleet_control_rpcs_bounded_per_lease(raft_eng, raft_single):
    """The coalesced control plane's gate, measured: a clean fleet's
    non-heartbeat transport turns per issued lease stay within the
    named constant (fleet.MAX_CONTROL_RPCS_PER_LEASE) — one acquire
    turn covers a worker's whole prefetched quantum and one batched
    turn reports it."""
    from madsim_tpu.fleet import MAX_CONTROL_RPCS_PER_LEASE

    fleet = fleet_sweep(None, raft_eng.cfg, RAFT_SEEDS, engine=raft_eng,
                        n_workers=2, range_size=16, **SWEEP_KW)
    assert_contract_equal(raft_single, fleet)
    stats = fleet.loop_stats["fleet"]
    assert stats["leases_prefetched"] >= 1
    assert stats["grouped_leases"] >= 2
    assert stats["session_reuse_hits"] >= 1
    assert stats["control_rpcs_per_lease"] <= MAX_CONTROL_RPCS_PER_LEASE
    turns = stats["rpc_turns"]
    # 4 ranges over 2 workers: one acquire turn per worker quantum plus
    # at most a few idle polls; completions ride batched turns.
    assert turns["acquire"] <= 2 * MAX_CONTROL_RPCS_PER_LEASE
    assert turns.get("batch", 0) >= 2
    assert turns.get("complete", 0) == 0  # completions only ride batches
    assert stats["acquire_s"] >= 0.0 and stats["sweep_s"] > 0.0
    assert "merge_s" in stats


# ---------------------------------------------------------------------------
# Multiprocess leg (real processes + signals) — excluded from tier-1
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_process_fleet_smoke(tmp_path):
    """Two real worker processes over pipes, one SIGKILLed mid-lease:
    the merged result still equals the single-host reference (recovery
    via lease TTL + respawn). Marked slow: each spawned worker pays a
    fresh JAX import + compile."""
    eng = DeviceEngine(RaftActor(RCFG), ECFG)
    seeds = np.arange(24)
    single = sweep(None, ECFG, seeds, engine=eng, **SWEEP_KW)
    fleet = fleet_sweep(RaftActor(RCFG), ECFG, seeds, n_workers=2,
                        range_size=8, spawn="process", lease_ttl=5.0,
                        checkpoint_dir=str(tmp_path / "ck"),
                        kill_after_heartbeats={"w0": 1},
                        serve_timeout_s=300.0, **SWEEP_KW)
    np.testing.assert_array_equal(single.bug, fleet.bug)
    for k in single.observations:
        np.testing.assert_array_equal(single.observations[k],
                                      fleet.observations[k], err_msg=k)
