"""Sweep observatory (docs/observability.md "The sweep observatory"):
live telemetry stream, Prometheus snapshots, profiler capture windows,
the `watch` CLI, and the bench_diff regression tool.

The load-bearing contracts: telemetry/profiling are host-side
observation only (observe-on and profile-on sweeps are bitwise
identical to plain ones), and the telemetry stream adds ZERO device→host
syncs — every record is built from the scalar batch the loop fetched
anyway (counted via the sweep module's ``_fetch`` hook, exactly like
tests/test_sweep_pipeline.py's sync-discipline test).
"""
import dataclasses
import importlib
import io
import json
import os

import numpy as np
import pytest

sweep_mod = importlib.import_module("madsim_tpu.parallel.sweep")
from madsim_tpu.engine import (
    DeviceEngine,
    EngineConfig,
    FAULT_KILL,
    FAULT_RESTART,
    RaftActor,
    RaftDeviceConfig,
)
from madsim_tpu.obs import observatory
from madsim_tpu.obs.cli import main as obs_main
from madsim_tpu.parallel.sweep import sweep

RAFT_FAULTS = np.array([[300_000, FAULT_KILL, 0, 0],
                        [700_000, FAULT_RESTART, 0, 0]], np.int32)

# The documented progress-record schema (docs/observability.md).
TELEMETRY_KEYS = {
    "schema", "elapsed_s", "chunks", "steps", "batch_worlds", "n_active",
    "occupancy", "seeds_total", "seeds_admitted", "seeds_done",
    "seeds_per_s", "world_utilization", "dispatch_depth", "bug_seen",
    "eta_s",
}


@pytest.fixture(scope="module")
def eng_on():
    rcfg = RaftDeviceConfig(n=3, n_proposals=2, buggy_double_vote=True)
    cfg = EngineConfig(n_nodes=3, outbox_cap=4, queue_cap=64,
                      t_limit_us=1_500_000, metrics=True)
    return DeviceEngine(RaftActor(rcfg), cfg)


@pytest.fixture(scope="module")
def eng_off():
    rcfg = RaftDeviceConfig(n=3, n_proposals=2, buggy_double_vote=True)
    cfg = EngineConfig(n_nodes=3, outbox_cap=4, queue_cap=64,
                      t_limit_us=1_500_000)
    return DeviceEngine(RaftActor(rcfg), cfg)


# ---------------------------------------------------------------------------
# Tier-1: telemetry schema on both orchestration paths (the
# test_loop_stats_schema_both_paths sibling for the observatory layer)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("pipeline", [True, False])
def test_telemetry_schema_both_paths(eng_on, pipeline):
    records = []
    res = sweep(None, eng_on.cfg, np.arange(24), engine=eng_on,
                chunk_steps=64, max_steps=2_048, faults=RAFT_FAULTS,
                pipeline=pipeline, observe=records.append)
    progress = [r for r in records if r.get("event") != "summary"]
    summary = [r for r in records if r.get("event") == "summary"]
    # One progress record per host read, plus exactly one summary.
    assert len(progress) == res.loop_stats["scalar_fetches"]
    assert len(summary) == 1
    for rec in progress:
        assert TELEMETRY_KEYS <= set(rec), sorted(rec)
        assert rec["schema"] == "madsim.sweep.telemetry/1"
        assert isinstance(rec["elapsed_s"], float) and rec["elapsed_s"] >= 0
        for key in ("chunks", "steps", "batch_worlds", "n_active",
                    "seeds_total", "seeds_admitted", "seeds_done",
                    "dispatch_depth"):
            assert isinstance(rec[key], int) and rec[key] >= 0, key
        assert 0.0 <= rec["occupancy"] <= 1.0
        assert rec["seeds_done"] <= rec["seeds_total"] == 24
        assert rec["eta_s"] is None or rec["eta_s"] >= 0.0
        # Coverage riders (metrics engine): distinct count + bucket width.
        assert rec["coverage_buckets"] == 256
        assert 0 <= rec["coverage_distinct"] <= 256
    # elapsed_s is monotonic within the stream (perf_counter-based).
    els = [r["elapsed_s"] for r in progress]
    assert els == sorted(els)
    # Progress coverage_distinct matches the result's novelty curve tail.
    assert progress[-1]["coverage_distinct"] == int(
        res.coverage.novelty_curve[-1])
    s = summary[0]
    assert s["loop_stats"] == res.loop_stats
    assert s["failing_seeds"] == len(res.failing_seeds)
    assert s["coverage"]["distinct_behaviors"] == \
        res.coverage.distinct_behaviors
    json.dumps(records)  # the whole stream is plain JSON


def test_telemetry_adds_zero_fetches_and_is_invisible(eng_on, monkeypatch):
    """Tier-1 sync discipline, observatory edition: with coverage AND a
    telemetry observer on, the loop still performs exactly one scalar
    _fetch per superstep (the novelty lane rides the same batch) plus
    the single final merge pull — and the observed sweep's results are
    bitwise identical to an unobserved one."""
    plain = sweep(None, eng_on.cfg, np.arange(40), engine=eng_on,
                  chunk_steps=64, max_steps=3_000, faults=RAFT_FAULTS)
    calls = []
    real_fetch = sweep_mod._fetch

    def counting_fetch(tree):
        out = real_fetch(tree)
        import jax
        calls.append(sum(np.asarray(x).nbytes
                         for x in jax.tree.leaves(out)))
        return out

    monkeypatch.setattr(sweep_mod, "_fetch", counting_fetch)
    records = []
    res = sweep(None, eng_on.cfg, np.arange(40), engine=eng_on,
                chunk_steps=64, max_steps=3_000, faults=RAFT_FAULTS,
                observe=records.append)
    st = res.loop_stats
    assert len(calls) == st["scalar_fetches"] + 1  # + final merge pull
    # Steady-state pulls stay a few hundred bytes even with the novelty
    # lane aboard — never a per-world array.
    assert max(calls[:-1]) <= 320, calls
    assert len(records) == st["scalar_fetches"] + 1  # + summary record
    for k, v in plain.observations.items():
        np.testing.assert_array_equal(v, res.observations[k], err_msg=k)
    np.testing.assert_array_equal(plain.coverage.hits, res.coverage.hits)


# ---------------------------------------------------------------------------
# Emitters: JSONL stream, watch CLI, Prometheus snapshots
# ---------------------------------------------------------------------------

def test_jsonl_stream_watch_cli_and_prometheus(eng_on, tmp_path, capsys):
    stream = str(tmp_path / "tele.jsonl")
    res = sweep(None, eng_on.cfg, np.arange(24), engine=eng_on,
                chunk_steps=64, max_steps=3_000, faults=RAFT_FAULTS,
                observe=stream)
    lines = [json.loads(ln) for ln in open(stream)]
    assert lines[-1]["event"] == "summary"
    assert len(lines) == res.loop_stats["scalar_fetches"] + 1

    # Summary mode of the CLI.
    prom = str(tmp_path / "snap.prom")
    rc = obs_main(["watch", stream, "--prom", prom])
    out = capsys.readouterr().out
    assert rc == 0
    assert "distinct behaviors" in out and "failing" in out
    text = open(prom).read()
    assert "# TYPE madsim_sweep_elapsed_s gauge" in text
    assert f"madsim_sweep_seeds_total {24}" in text

    # Follow mode over a completed stream: tails every record, prints
    # the summary, and returns without blocking.
    buf = io.StringIO()
    rc = observatory.watch(stream, follow=True, interval=0.01, out=buf)
    assert rc == 0
    tail = buf.getvalue()
    assert tail.count("chunks=") >= res.loop_stats["scalar_fetches"]
    assert "behaviors=" in tail

    # Missing file → usage-style exit.
    assert observatory.watch(str(tmp_path / "nope.jsonl")) == 2


def test_watch_renders_exchange_records_interleaved(tmp_path):
    """The `madsim.fleet.exchange/1` schema (PR 12): `watch --follow`
    renders exchange events interleaved with the sweep and fleet
    schemas, and the summary mode rolls them up — round-tripped through
    a real JSONL stream."""
    stream = str(tmp_path / "mixed.jsonl")
    records = [
        {"schema": "madsim.sweep.telemetry/1", "elapsed_s": 0.5,
         "chunks": 3, "n_active": 8, "batch_worlds": 16,
         "seeds_total": 32, "seeds_done": 4, "seeds_per_s": 8.0},
        {"schema": "madsim.fleet.telemetry/1", "event": "lease_issued",
         "t": 1, "worker": "w0", "range_id": 0, "lease_id": 0,
         "generation": 0},
        {"schema": "madsim.fleet.exchange/1", "event": "publish", "t": 2,
         "worker": "w0", "range_id": 0, "epoch": 0, "bytes": 3360,
         "duplicate": False, "corpus_size": 2},
        {"schema": "madsim.fleet.exchange/1", "event": "merge", "t": 3,
         "epoch": 0, "ranges_merged": 2, "corpus_inserted": 5,
         "corpus_size": 6, "corpus_gen": 1, "epochs_merged": 1},
        {"schema": "madsim.fleet.exchange/1", "event": "broadcast",
         "t": 4, "worker": "w1", "range_id": 2, "epoch": 1,
         "from_epoch": 0, "bytes": 3360},
        {"schema": "madsim.fleet.exchange/1", "event": "publish_torn",
         "t": 5, "worker": "w1", "range_id": 2, "epoch": 1,
         "error": "checksum mismatch"},
        {"schema": "madsim.sweep.telemetry/1", "event": "summary",
         "elapsed_s": 1.0, "seeds_total": 32, "failing_seeds": 0,
         "world_utilization": 0.9, "loop_stats": {"chunks": 6,
                                                  "dispatches": 3}},
    ]
    with open(stream, "w", encoding="utf-8") as f:
        for r in records:
            f.write(json.dumps(r) + "\n")

    # Follow mode: one rendered line per record, all three schemas
    # interleaved in stream order.
    buf = io.StringIO()
    assert observatory.watch(stream, follow=True, interval=0.01,
                             out=buf) == 0
    tail = buf.getvalue()
    assert "[exchange]" in tail
    assert "publish" in tail and "merge" in tail and "broadcast" in tail
    assert "epoch=0" in tail and "ranges_merged=2" in tail
    assert "corpus_inserted=5" in tail and "corpus_gen=1" in tail
    assert "bytes=3360" in tail
    assert "publish_torn" in tail and "error=checksum mismatch" in tail
    assert "[w0]" in tail and "lease_issued" in tail  # fleet schema
    assert "chunks=3" in tail                         # sweep schema

    # Summary mode: the exchange rollup line sits beside the sweep
    # summary.
    buf = io.StringIO()
    assert observatory.watch(stream, out=buf) == 0
    text = buf.getvalue()
    assert "exchange: 1 epoch(s) merged, 5 corpus insert(s)" in text
    assert "1 torn publish(es) discarded" in text
    assert "merged corpus: 6 entries after epoch 0" in text
    assert "final: 0 failing of 32 seeds" in text


def test_exchange_stream_from_real_fleet_run(tmp_path):
    """End-to-end: an exchanged guided fleet writes its telemetry to a
    JSONL sink; the stream carries all three schemas and `watch`
    summarizes it without error."""
    from madsim_tpu.fleet import ExchangeConfig, fleet_sweep
    from madsim_tpu.search import (
        GuidedPairActor,
        GuidedPairConfig,
        engine_config,
        family_schedule,
    )
    from madsim_tpu.search.family import HUNT_NODES, HUNT_ROWS, \
        hunt_search_config

    acfg = GuidedPairConfig(n=HUNT_NODES)
    eng = DeviceEngine(GuidedPairActor(acfg), engine_config(acfg))
    stream = str(tmp_path / "fleet.jsonl")
    fleet_sweep(None, eng.cfg, np.arange(96), engine=eng,
                faults=family_schedule(HUNT_ROWS, acfg), n_workers=2,
                range_size=48, recycle=True, batch_worlds=32,
                chunk_steps=32, max_steps=10_000_000,
                search=hunt_search_config(True),
                exchange=ExchangeConfig(every=1), observe=stream)
    recs = [json.loads(ln) for ln in open(stream) if ln.strip()]
    schemas = {r.get("schema") for r in recs}
    assert "madsim.fleet.exchange/1" in schemas
    assert "madsim.fleet.telemetry/1" in schemas
    ex = [r for r in recs if r.get("schema") == "madsim.fleet.exchange/1"]
    events = {r["event"] for r in ex}
    assert {"publish", "merge", "broadcast"} <= events
    merge = next(r for r in ex if r["event"] == "merge")
    assert {"epoch", "ranges_merged", "corpus_inserted",
            "corpus_size"} <= set(merge)
    pub = next(r for r in ex if r["event"] == "publish")
    assert pub["bytes"] > 0
    buf = io.StringIO()
    assert observatory.watch(stream, out=buf) == 0
    assert "exchange:" in buf.getvalue()


def test_make_observer_contract(tmp_path):
    assert observatory.make_observer(None) == (None, None)
    sink = []
    emit, close = observatory.make_observer(sink.append)
    emit({"x": 1})
    assert sink == [{"x": 1}] and close is None
    with pytest.raises(TypeError, match="observe"):
        observatory.make_observer(42)
    path = tmp_path / "s.jsonl"
    emit, close = observatory.make_observer(str(path))
    emit({"a": True})
    close()
    assert json.loads(path.read_text()) == {"a": True}


def test_prometheus_text_shape():
    text = observatory.prometheus_text(
        {"seeds_per_s": 12.5, "bug_seen": True, "note": "skip-me",
         "eta_s": None, "loop_stats": {"nested": 1}})
    assert "madsim_sweep_seeds_per_s 12.5" in text
    assert "madsim_sweep_bug_seen 1" in text
    assert "note" not in text and "nested" not in text


# ---------------------------------------------------------------------------
# Profiler capture window
# ---------------------------------------------------------------------------

def test_profile_dir_captures_and_stays_invisible(eng_off, tmp_path):
    """sweep(profile_dir=...) lands a device-timeline capture under the
    directory and changes nothing about the results (bitwise) or the
    dispatch schedule."""
    plain = sweep(None, eng_off.cfg, np.arange(24), engine=eng_off,
                  chunk_steps=64, max_steps=2_048)
    pdir = str(tmp_path / "prof")
    prof = sweep(None, eng_off.cfg, np.arange(24), engine=eng_off,
                 chunk_steps=64, max_steps=2_048, profile_dir=pdir,
                 profile_window=(0, 2))
    files = [os.path.join(r, fn) for r, _d, fns in os.walk(pdir)
             for fn in fns]
    assert files, "profiler window captured nothing"
    for k, v in plain.observations.items():
        np.testing.assert_array_equal(v, prof.observations[k], err_msg=k)
    assert plain.loop_stats["dispatches"] == prof.loop_stats["dispatches"]


def test_profile_window_validation(eng_off, tmp_path):
    with pytest.raises(ValueError, match="profile_window"):
        sweep(None, eng_off.cfg, np.arange(8), engine=eng_off,
              chunk_steps=64, max_steps=256,
              profile_dir=str(tmp_path / "p"), profile_window=(3, 3))
    # window is ignored entirely when no profile_dir is given.
    observatory.ProfilerWindow(None, (9, 9)).before_dispatch()


# ---------------------------------------------------------------------------
# tools/bench_diff.py — the regression table
# ---------------------------------------------------------------------------

def _bench_doc(seeds_per_sec, flops, distinct=8):
    return {
        "metric": "madraft_3node_1s_seeds_per_sec",
        "value": seeds_per_sec, "unit": "seeds/s", "vs_baseline": 100.0,
        "configs": {
            "madraft_5node": {
                "seeds_per_sec": seeds_per_sec / 10,
                "world_utilization": 0.9,
                "xla_cost": {"flops_per_world_step": flops},
                "sweep_loop": {"chunks_per_dispatch": 4.0,
                               "host_decision_s": 0.01,
                               "loop_wall_s": 1.0},
                "coverage": {"distinct_behaviors": distinct},
            },
        },
    }


def test_bench_diff_table_and_regression_gate(tmp_path, capsys):
    import tools.bench_diff as bd

    old = tmp_path / "old.json"
    new = tmp_path / "new.json"
    old.write_text(json.dumps(_bench_doc(100_000.0, 8_000.0)))
    # Faster headline, but a flop regression past any threshold.
    new.write_text(json.dumps(_bench_doc(120_000.0, 16_000.0)))
    rc = bd.main([str(old), str(new)])
    out = capsys.readouterr().out
    assert rc == 0  # informational by default
    assert "headline seeds/s" in out and "+20.0%" in out
    assert "REGRESSED" in out  # flops doubled, lower-is-better
    rc = bd.main([str(old), str(new), "--fail-on-regress", "50"])
    assert rc == 1  # the 100% flop regression trips the gate
    rc = bd.main([str(old), str(new), "--fail-on-regress", "150"])
    assert rc == 0  # within tolerance


def test_bench_diff_loads_wrapper_shapes(tmp_path):
    import tools.bench_diff as bd

    doc = _bench_doc(50_000.0, 7_000.0)
    raw = tmp_path / "bench_results.json"
    raw.write_text(json.dumps(doc))
    assert bd.load_round(str(raw))["value"] == 50_000.0
    wrapped = tmp_path / "BENCH_r09.json"
    wrapped.write_text(json.dumps({"n": 9, "rc": 0, "parsed": doc}))
    assert bd.load_round(str(wrapped))["value"] == 50_000.0
    # parsed=null with the result's JSON line surviving in the tail.
    tail = tmp_path / "BENCH_r10.json"
    tail.write_text(json.dumps(
        {"n": 10, "rc": 0, "parsed": None,
         "tail": "noise\n" + json.dumps(doc) + "\n"}))
    assert bd.load_round(str(tail))["value"] == 50_000.0
    # Unrecoverable (head-truncated) tail → a clear error.
    bad = tmp_path / "BENCH_r11.json"
    bad.write_text(json.dumps({"n": 11, "parsed": None,
                               "tail": "…cut} {also-not-json"}))
    with pytest.raises(ValueError, match="no parsable result"):
        bd.load_round(str(bad))


def test_watch_renders_fused_search_cadence():
    """Fused-hunt search telemetry (docs/observability.md "Fused-sweep
    cadence"): records labeled with ``epochs_on_device`` render as
    explicit per-mega-dispatch rollups, and the summary rollup notes
    ``fused=true`` — while unlabeled (host-refill) records keep the
    per-refill rendering."""
    fused_rec = {"schema": "madsim.search.telemetry/1", "event": "refill",
                 "elapsed_s": 1.25, "generation": 3, "corpus_size": 17,
                 "corpus_inserted": 16, "refill_novel": 2,
                 "refill_inserted": 2, "epochs_on_device": 5}
    host_rec = {k: v for k, v in fused_rec.items()
                if k != "epochs_on_device"}
    line = observatory.render_search_event(fused_rec)
    assert "epochs_on_device=5 (per-mega-dispatch rollup)" in line
    assert "epochs_on_device" not in \
        observatory.render_search_event(host_rec)
    rollup = "\n".join(observatory.render_search_summary([fused_rec]))
    assert "fused=true" in rollup and "mega-dispatch rollup" in rollup
    assert "5 refill epoch(s) ran on device" in rollup
    host_rollup = "\n".join(observatory.render_search_summary([host_rec]))
    assert "fused" not in host_rollup and "refill(s)" in host_rollup
