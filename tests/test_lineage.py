"""The evolution observatory (madsim_tpu/obs/lineage.py, PR 13).

The contract (docs/search.md "Reading the lineage"):

- lineage-on is BITWISE identical to lineage-off on everything the
  simulation produces — trajectories, observations, materialized
  schedules, the corpus decision surface (the pinned fuzz-demo numbers
  ride on this: mutation bytes are sacred per the PR 11 retune rule);
- zero added host syncs: the provenance lanes and the operator outcome
  table ride the retire pulls and the final fetch the guided loop
  already pays (counted through the ``_fetch`` hook);
- checkpoint→resume restores the lanes, the corpus lineage lanes, and
  the outcome table bit-exactly (PR 7 aux channel); lineage on/off
  checkpoint mixups are refused loudly;
- ancestry chains reconstruct host-side from parent entry ids down to
  the generation-0 template, across fleet ranges in a merged report;
- the device outcome fold equals the host twin (host_credit /
  host_harvest_fold masks — parity also gated in tests/test_exchange);
- the surfaces exist: SearchReport.lineage/operator_stats/summary(),
  SweepResult.summary() mentions the hunt, the
  ``madsim.search.telemetry/1`` stream renders in ``obs watch`` and the
  per-schema Prometheus snapshot, and triage bundles carry a
  ``madsim.search.lineage/1`` block the ``obs lineage`` CLI renders.

Compile budget: one module-scoped family engine at the same
(batch_worlds=32, chunk_steps=32) shapes as tests/test_search.py.
"""
import dataclasses as dc
import importlib
import io
import json

import numpy as np
import pytest

from madsim_tpu.engine import DeviceEngine
from madsim_tpu.engine.checkpoint import CheckpointError
from madsim_tpu.obs import lineage as L
from madsim_tpu.search import (
    GuidedPairActor,
    GuidedPairConfig,
    engine_config,
    family_schedule,
)
from madsim_tpu.search.family import HUNT_NODES, HUNT_ROWS, hunt_search_config

sweep_mod = importlib.import_module("madsim_tpu.parallel.sweep")
sweep = sweep_mod.sweep

BATCH = dict(recycle=True, batch_worlds=32, chunk_steps=32)


@pytest.fixture(scope="module")
def hunt():
    acfg = GuidedPairConfig(n=HUNT_NODES)
    cfg = engine_config(acfg)
    eng = DeviceEngine(GuidedPairActor(acfg), cfg)
    return eng, cfg, family_schedule(HUNT_ROWS, acfg)


def _guided(eng, cfg, tmpl, n_seeds, lineage=True, guided=True,
            max_steps=10_000_000, **kw):
    scfg = dc.replace(hunt_search_config(guided), lineage=lineage)
    return sweep(None, cfg, np.arange(n_seeds), engine=eng, faults=tmpl,
                 max_steps=max_steps, search=scfg, **BATCH, **kw)


@pytest.fixture(scope="module")
def find(hunt):
    """One guided stop-on-first-bug hunt with lineage on — shared by
    every test that only READS the report."""
    eng, cfg, tmpl = hunt
    return _guided(eng, cfg, tmpl, 128, stop_on_first_bug=True)


# ---------------------------------------------------------------------------
# Bitwise invisibility: lineage on == lineage off
# ---------------------------------------------------------------------------

def test_lineage_on_equals_off_bitwise(hunt, find):
    """The accounting must be write-only: same trajectories, same
    materialized schedules, same corpus decisions — the masks are the
    generator's existing intermediates, exposed not recomputed."""
    eng, cfg, tmpl = hunt
    off = _guided(eng, cfg, tmpl, 128, lineage=False,
                  stop_on_first_bug=True)
    on = find
    assert on.failing_seeds, "the guided hunt must reach the bug"
    assert (on.bug == off.bug).all()
    for k in on.observations:
        np.testing.assert_array_equal(np.asarray(on.observations[k]),
                                      np.asarray(off.observations[k]),
                                      err_msg=k)
    np.testing.assert_array_equal(on.search.schedules,
                                  off.search.schedules)
    for f in ("corpus_sched", "corpus_sig", "corpus_score",
              "corpus_filled"):
        np.testing.assert_array_equal(getattr(on.search, f),
                                      getattr(off.search, f), err_msg=f)
    assert on.search.generations == off.search.generations
    assert on.search.inserted == off.search.inserted
    np.testing.assert_array_equal(on.coverage.hits, off.coverage.hits)
    # Only the observability surface differs.
    assert on.search.lineage is not None
    assert on.search.operator_stats is not None
    assert off.search.lineage is None
    assert off.search.operator_stats is None


# ---------------------------------------------------------------------------
# The report surface: ancestry, outcome identities, summaries
# ---------------------------------------------------------------------------

def test_find_ancestry_reaches_template_with_operators(find):
    rep = find.search
    s0 = find.failing_seeds[0]
    chain = rep.ancestry(s0, seeds=find.seeds)
    assert chain[0]["seed"] == s0
    assert chain[-1]["kind"] == "template"
    # Depths strictly decrease along the chain's world nodes.
    depths = [n["depth"] for n in chain if n["kind"] == "world"]
    assert depths == sorted(depths, reverse=True)
    assert depths[0] == rep.lineage.depth[int(s0)]
    # The pair bug is unreachable without mutation: operators named.
    assert {op for n in chain for op in n.get("ops", [])}
    # Rendering covers every hop.
    text = L.render_tree(chain)
    assert "template (entry 0" in text
    assert f"seed {s0}" in text


def test_operator_outcome_identities(find):
    """Structural identities of the outcome table: every survivor was
    novel, every credited retiring world was an installed child, and
    the host-side bug fold credits the find's operators."""
    st = find.search.operator_stats
    assert set(st) == set(L.OP_NAMES)
    assert sum(r["produced"] for r in st.values()) > 0
    for name, row in st.items():
        assert 0 <= row["survived"] <= row["novel"], (name, row)
        assert row["survived"] <= row["produced"], (name, row)
        assert row["bug"] <= row["produced"], (name, row)
    # The find carried at least one operator — its bits got bug credit.
    s0 = find.failing_seeds[0]
    ops = L.op_names(int(find.search.lineage.ops[int(s0)]))
    assert ops and all(st[o]["bug"] >= 1 for o in ops)
    # summary() renders the effectiveness table.
    text = find.search.summary()
    assert "top operator" in text and "survived" in text


def test_sweep_summary_and_banner_mention_the_hunt(find):
    text = find.summary()
    assert "guided search: corpus" in text
    assert "top operator" in text
    assert "guided search" in find.repro_banner()


# ---------------------------------------------------------------------------
# Sync discipline: zero added host pulls
# ---------------------------------------------------------------------------

def test_lineage_adds_zero_host_syncs(hunt, monkeypatch):
    eng, cfg, tmpl = hunt
    calls = []
    real_fetch = sweep_mod._fetch

    def counting_fetch(tree):
        calls.append(1)
        return real_fetch(tree)

    monkeypatch.setattr(sweep_mod, "_fetch", counting_fetch)
    res = _guided(eng, cfg, tmpl, 96)
    st = res.loop_stats
    assert st["retire_fetches"] >= 1
    assert len(calls) == st["scalar_fetches"] + st["retire_fetches"] + 1
    assert res.search.lineage is not None


# ---------------------------------------------------------------------------
# Checkpoint → resume: lanes + outcome table bit-exact
# ---------------------------------------------------------------------------

def test_checkpoint_resume_restores_lineage_bit_exact(hunt, tmp_path):
    eng, cfg, tmpl = hunt
    unbroken = _guided(eng, cfg, tmpl, 96)
    path = str(tmp_path / "lin.npz")
    _part = _guided(eng, cfg, tmpl, 96, max_steps=64 * 32,
                    checkpoint_path=path, checkpoint_every_chunks=4)
    full = _guided(eng, cfg, tmpl, 96, checkpoint_path=path, resume=True)
    for f in ("parent1", "parent2", "ops", "depth"):
        np.testing.assert_array_equal(
            getattr(unbroken.search.lineage, f),
            getattr(full.search.lineage, f), err_msg=f)
    assert unbroken.search.operator_stats == full.search.operator_stats
    np.testing.assert_array_equal(unbroken.search.corpus_entry,
                                  full.search.corpus_entry)
    np.testing.assert_array_equal(unbroken.search.corpus_depth,
                                  full.search.corpus_depth)
    # Lineage on/off mixups are refused with a pointed error.
    with pytest.raises(CheckpointError, match="lineage"):
        _guided(eng, cfg, tmpl, 96, lineage=False, checkpoint_path=path,
                resume=True)


# ---------------------------------------------------------------------------
# Host/device outcome-fold parity (the credit twin)
# ---------------------------------------------------------------------------

def test_host_credit_matches_device_credit():
    import jax.numpy as jnp

    rng = np.random.RandomState(3)
    for _ in range(8):
        w = int(rng.randint(1, 40))
        ops = rng.randint(0, 32, size=(w,)).astype(np.int8)
        mask = rng.rand(w) < 0.5
        base = rng.randint(0, 100, size=(L.N_OPS,)).astype(np.int32)
        dev = L.credit(jnp.asarray(base), L.ops_bits(jnp.asarray(ops)),
                       jnp.asarray(mask))
        host = L.host_credit(base, ops, mask)
        np.testing.assert_array_equal(np.asarray(dev), host)


def test_lineage_lane_unit_helpers():
    import jax.numpy as jnp

    # pack/unpack round-trip over all 32 masks.
    masks = np.arange(32, dtype=np.int32)
    bits = L.host_ops_bits(masks)
    packed = L.pack_ops([jnp.asarray(bits[:, i]) for i in range(L.N_OPS)])
    assert packed.dtype == jnp.int8
    np.testing.assert_array_equal(np.asarray(packed), masks.astype(np.int8))
    np.testing.assert_array_equal(
        np.asarray(L.ops_bits(jnp.asarray(masks.astype(np.int8)))), bits)
    assert L.op_names(0b10001) == ["splice", "op_flip"]
    # Origin lanes: generation 0, no parents, depth 0.
    lanes = L.lanes_origin(4)
    assert (np.asarray(lanes.p1) == L.NO_PARENT).all()
    assert (np.asarray(lanes.depth) == 0).all()


def test_ancestry_unit_resolution_and_externals():
    # Hand-built per-seed table: 0 = gen-0 world; 1 = child of entry 1
    # (seed 0); 2 = child of entry 99 (external/exchange-seeded).
    lin = L.SearchLineage(
        parent1=np.asarray([-1, 1, 99], np.int32),
        parent2=np.asarray([-1, 1, 99], np.int32),
        ops=np.asarray([0, 0b01000, 0b00100], np.int32),
        depth=np.asarray([0, 1, 7], np.int32))
    chain = L.ancestry(lin, 1)
    assert [n["kind"] for n in chain] == ["world", "world", "template"]
    assert chain[0]["ops"] == ["node_rotate"]
    ext = L.ancestry(lin, 2)
    assert ext[-1]["kind"] == "external" and ext[-1]["entry"] == 99
    assert "external entry 99" in L.render_tree(ext)
    # entry_base arithmetic: a range at lo=48 resolves 48-based entries.
    lin48 = L.SearchLineage(parent1=np.asarray([-1, 50], np.int32),
                            parent2=np.asarray([-1, 50], np.int32),
                            ops=np.zeros(2, np.int32),
                            depth=np.asarray([0, 1], np.int32),
                            entry_base=48)
    assert lin48.resolve(50) == 1
    assert lin48.resolve(3) is None       # another range's entry
    assert lin48.resolve(L.TEMPLATE_ENTRY) is None


def test_merge_operator_stats_and_top():
    a = L.operator_stats(np.asarray([4, 0, 0, 0, 0]),
                         np.asarray([2, 0, 0, 0, 0]),
                         np.asarray([1, 0, 0, 0, 0]),
                         np.asarray([0, 0, 0, 0, 0]))
    b = L.operator_stats(np.asarray([4, 0, 8, 0, 0]),
                         np.asarray([2, 0, 6, 0, 0]),
                         np.asarray([1, 0, 4, 0, 0]),
                         np.asarray([1, 0, 0, 0, 0]))
    merged = L.merge_operator_stats([a, b])
    assert merged["splice"]["produced"] == 8
    assert merged["splice"]["survived"] == 2
    assert merged["splice"]["survival_pct"] == 25.0
    assert L.top_operator(merged) == "time_jitter"
    assert L.top_operator(None) is None


# ---------------------------------------------------------------------------
# Telemetry stream + Prometheus per-schema counters (satellite)
# ---------------------------------------------------------------------------

def test_search_telemetry_stream_watch_and_prom(hunt, tmp_path):
    from madsim_tpu.obs import observatory

    eng, cfg, tmpl = hunt
    stream = str(tmp_path / "tele.jsonl")
    res = _guided(eng, cfg, tmpl, 128, stop_on_first_bug=True,
                  observe=stream)
    recs = [json.loads(ln) for ln in open(stream) if ln.strip()]
    srch = [r for r in recs
            if r.get("schema") == "madsim.search.telemetry/1"]
    assert len(srch) == res.loop_stats["retire_fetches"]
    need = {"event", "generation", "corpus_size", "corpus_inserted",
            "refill_novel", "refill_inserted", "op_produced_splice",
            "op_survived_node_rotate"}
    assert all(need <= set(r) for r in srch), srch[0]
    summ = next(r for r in recs if r.get("event") == "summary")
    assert summ["search"]["operator_stats"]
    assert summ["search"]["finds"][0]["schema"] == L.LINEAGE_SCHEMA
    # watch renders the search schema in follow and summary modes.
    buf = io.StringIO()
    assert observatory.watch(stream, follow=True, interval=0.01,
                             out=buf) == 0
    tail = buf.getvalue()
    assert "[search]" in tail and "corpus=" in tail
    buf = io.StringIO()
    assert observatory.watch(stream, out=buf) == 0
    assert "search:" in buf.getvalue()
    # The Prometheus snapshot carries per-schema counters + both gauge
    # families (the satellite: fleet/search activity must not vanish
    # behind the newest record).
    text = observatory.prometheus_snapshot(recs)
    assert "madsim_records_sweep" in text
    assert "madsim_records_search" in text
    assert "madsim_sweep_seeds_total" in text
    assert "madsim_search_corpus_size" in text


def test_prometheus_snapshot_counts_fleet_and_exchange_schemas():
    from madsim_tpu.obs import observatory

    recs = [
        {"schema": "madsim.sweep.telemetry/1", "n_active": 3,
         "seeds_total": 8},
        {"schema": "madsim.fleet.telemetry/1", "event": "lease_issued"},
        {"schema": "madsim.fleet.telemetry/1", "event": "lease_issued"},
        {"schema": "madsim.fleet.exchange/1", "event": "publish"},
        {"schema": "madsim.search.telemetry/1", "event": "refill",
         "corpus_size": 2},
    ]
    text = observatory.prometheus_snapshot(recs)
    assert "madsim_records_fleet 2" in text
    assert "madsim_records_exchange 1" in text
    assert "madsim_fleet_events_lease_issued 2" in text
    assert "madsim_exchange_events_publish 1" in text
    assert "madsim_sweep_n_active 3" in text
    assert "madsim_search_corpus_size 2" in text


# ---------------------------------------------------------------------------
# Bundles + the `obs lineage` CLI
# ---------------------------------------------------------------------------

def test_triage_bundle_carries_lineage_and_cli_renders(find, tmp_path,
                                                       capsys):
    from madsim_tpu.obs.cli import main as obs_main
    from madsim_tpu.triage import triage

    report = triage(find, out_dir=str(tmp_path), chunk_steps=32,
                    max_steps=20_000)
    bundle_path = list(report.bundles.values())[0]
    bundle = json.load(open(bundle_path))
    block = bundle["lineage"]
    assert block["schema"] == L.LINEAGE_SCHEMA
    assert block["seed"] == find.failing_seeds[0]
    assert block["operators_applied"]
    assert block["chain"][-1]["kind"] == "template"
    assert set(block["operator_stats"]) == set(L.OP_NAMES)
    # The CLI renders the tree + the outcome table, exit 0.
    assert obs_main(["lineage", bundle_path]) == 0
    out = capsys.readouterr().out
    assert "template (entry 0" in out
    assert "operator" in out and "survived" in out
    # A lineage-free file exits 2 with a pointed message.
    plain = tmp_path / "plain.json"
    plain.write_text(json.dumps({"version": 1, "kind": "host_test"}))
    assert obs_main(["lineage", str(plain)]) == 2


# ---------------------------------------------------------------------------
# Fleet: merged reports resolve ancestry across ranges
# ---------------------------------------------------------------------------

def test_fleet_merged_lineage_resolves_across_ranges(hunt):
    """Each range writes entry ids at base range.lo, so the merged
    per-seed table resolves any parent with entry-1 arithmetic — and
    an exchanged fleet's later epochs may point at earlier ranges'
    entries (cross-range attribution, the PR 13 fleet satellite)."""
    from madsim_tpu.fleet import ExchangeConfig, fleet_sweep

    eng, cfg, tmpl = hunt
    res = fleet_sweep(None, cfg, np.arange(96), engine=eng, faults=tmpl,
                      n_workers=2, range_size=48, max_steps=10_000_000,
                      search=hunt_search_config(True),
                      exchange=ExchangeConfig(every=1), **BATCH)
    lin = res.search.lineage
    assert lin is not None and lin.entry_base == 0
    assert lin.parent1.shape == (96,)
    # Range-1 children (rows 48+) carry parents; every in-fleet parent
    # entry resolves to a real seed position.
    p = lin.parent1[48:]
    real = p[p > 0]
    assert real.size, "epoch-1 ranges generated no children?"
    for e in real:
        pos = lin.resolve(int(e))
        assert pos is None or 0 <= pos < 96
    # At least one range-1 world descends from a range-0 entry (the
    # exchange seeded epoch 1 from epoch 0's merged corpus).
    assert any(lin.resolve(int(e)) is not None and lin.resolve(int(e)) < 48
               for e in real), \
        "no cross-range ancestry: exchange lineage is not merging"
    # Ancestry from a range-1 world chains through without error.
    pos = 48 + int(np.flatnonzero(p > 0)[0])
    chain = res.search.ancestry(pos)
    assert chain[-1]["kind"] in ("template", "external")
    # The merged operator table sums the ranges'.
    assert sum(r["produced"]
               for r in res.search.operator_stats.values()) > 0
