"""Test configuration: force JAX onto a virtual 8-device CPU mesh.

Multi-chip TPU hardware is not available in CI; sharding correctness is
validated on host CPU devices (the driver separately dry-run-compiles the
multi-chip path via __graft_entry__.dryrun_multichip).

Note: the JAX_PLATFORMS env var alone is not honored when an accelerator
PJRT plugin is installed, so the platform is also pinned via jax.config.
"""
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402  (import after env setup)

jax.config.update("jax_platforms", "cpu")
