"""Test configuration: force JAX onto a virtual 8-device CPU mesh.

Multi-chip TPU hardware is not available in CI; sharding correctness is
validated on host CPU devices (the driver separately dry-run-compiles the
multi-chip path via __graft_entry__.dryrun_multichip).

Note: the JAX_PLATFORMS env var alone is not honored when an accelerator
PJRT plugin is installed, so the platform is also pinned via jax.config.
"""
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402  (import after env setup)

jax.config.update("jax_platforms", "cpu")

# Persistent XLA compilation cache (repo-local, gitignored). The suite
# builds many fresh DeviceEngines with IDENTICAL configs across test
# files — raft n=3 buggy, pb, tpc all recur — and jit caches are
# per-engine-instance, so without this every file re-pays the same
# multi-second XLA compiles. The on-disk cache is HLO-keyed: identical
# programs compile once per machine (first run populates, repeat runs
# and later files hit), which is what keeps the growing tier-1 suite
# inside its wall-clock budget on small CI boxes. Correctness-neutral:
# the cache stores compiled executables keyed by program + flags, and
# bitwise determinism of results is separately pinned by the
# crosscheck/determinism tests.
jax.config.update(
    "jax_compilation_cache_dir",
    os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                 ".jax_cache"))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
