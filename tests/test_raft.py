"""MadRaft-equivalent workload tests: election + replication under chaos.

These are the benchmark configs from BASELINE.md exercised as correctness
tests (3-node election, 5-node replication, partitions, crash-restart).
"""
import pytest

import madsim_tpu as ms
from madsim_tpu import rand, time
from madsim_tpu.models.raft import RaftCluster, RaftOptions


def test_initial_election_3():
    @ms.test(seed=1, count=5, time_limit=60.0)
    async def t():
        cluster = RaftCluster(3)
        leader = await cluster.wait_for_leader()
        assert leader in (0, 1, 2)
        # Terms are small and agree on one leader
        await time.sleep(1.0)
        assert cluster.leader() is not None

    t()


def test_election_after_leader_kill():
    @ms.test(seed=3, count=3, time_limit=120.0)
    async def t():
        cluster = RaftCluster(3)
        first = await cluster.wait_for_leader()
        cluster.kill(first)
        await time.sleep(1.0)
        second = await cluster.wait_for_leader()
        assert second != first
        # old leader rejoins as follower
        cluster.restart(first)
        await time.sleep(2.0)
        assert cluster.leader() is not None

    t()


def test_log_replication():
    @ms.test(seed=5, count=3, time_limit=120.0)
    async def t():
        cluster = RaftCluster(3)
        await cluster.wait_for_leader()
        for i in range(10):
            await cluster.propose(f"cmd-{i}")
        await time.sleep(2.0)
        # All live servers applied the same commands in order
        applied = [tuple(s.applied) for s in cluster.servers.values()]
        assert tuple(f"cmd-{i}" for i in range(10)) == applied[0][:10]
        assert all(a[:10] == applied[0][:10] for a in applied)
        assert len(cluster.checker.committed) >= 10

    t()


def test_replication_survives_minority_failure():
    @ms.test(seed=7, count=2, time_limit=240.0)
    async def t():
        cluster = RaftCluster(5)
        await cluster.wait_for_leader()
        await cluster.propose("before")
        # kill two followers (minority)
        leader = cluster.leader()
        victims = [i for i in range(5) if i != leader][:2]
        for v in victims:
            cluster.kill(v)
        await cluster.propose("during", timeout=30.0)
        for v in victims:
            cluster.restart(v)
        await cluster.propose("after", timeout=30.0)
        await time.sleep(3.0)
        live = [s for i, s in cluster.servers.items()]
        commands = [a for a in live[0].applied]
        assert "before" in commands and "during" in commands and "after" in commands

    t()


def test_partition_minority_cannot_commit():
    @ms.test(seed=11, count=2, time_limit=240.0)
    async def t():
        cluster = RaftCluster(5)
        leader = await cluster.wait_for_leader()
        minority = [leader, (leader + 1) % 5]
        majority = [i for i in range(5) if i not in minority]
        cluster.partition(minority, majority)
        await time.sleep(2.0)
        # majority elects a new leader
        new_leader = cluster.leader()
        assert new_leader in majority
        old_commit = cluster.servers[leader].commit_index
        # propose via the majority leader; minority leader cannot commit
        await cluster.propose("majority-cmd", timeout=30.0)
        assert cluster.servers[leader].commit_index == old_commit
        cluster.heal()
        await time.sleep(3.0)
        # after heal, the old leader catches up and has the new command
        assert "majority-cmd" in cluster.servers[leader].applied

    t()


def test_raft_chaos_determinism():
    """Same seed -> identical committed log across chaotic runs."""

    def run(seed):
        rt = ms.Runtime(seed=seed)
        rt.set_time_limit(600.0)

        async def main():
            cluster = RaftCluster(3)
            await cluster.wait_for_leader()
            for i in range(20):
                await cluster.propose(("op", i), timeout=60.0)
                if rand.gen_bool(0.3):
                    victim = rand.gen_range(0, 3)
                    cluster.restart(victim)
                    await time.sleep(0.2)
            return tuple(cluster.checker.committed)

        return rt.block_on(main())

    a = run(99)
    b = run(99)
    assert a == b
    assert len(a) >= 20


def test_raft_seed_sweep_no_invariant_violations():
    """A small sweep of chaotic seeds; the invariant checker is the bug flag
    (election safety + log matching) and must stay quiet."""

    @ms.test(seed=200, count=8, time_limit=600.0)
    async def t():
        cluster = RaftCluster(3)
        await cluster.wait_for_leader()
        for i in range(5):
            await cluster.propose(i, timeout=60.0)
            victim = rand.gen_range(0, 3)
            action = rand.gen_range(0, 3)
            if action == 0:
                cluster.restart(victim)
            elif action == 1:
                others = [j for j in range(3) if j != victim]
                cluster.partition([victim], others)
                await time.sleep(rand.random())
                cluster.heal()
            await time.sleep(0.1)
        await cluster.propose("final", timeout=60.0)
        assert "final" in [c[1] for c in cluster.checker.committed]

    t()
